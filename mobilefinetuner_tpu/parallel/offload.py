"""Budget-driven parameter placement: HBM vs pinned host RAM.

TPU-native analog of the reference's ZeRO-inspired single-device
ParameterSharder (reference: operators/opt_ops/sharding/parameter_sharder.{h,cpp}):
the reference tiers parameters between RAM and local disk under a byte budget
(`max_resident_bytes`), optionally FP16-quantizing on write
(parameter_sharder.cpp:215-232), and models call `require(name)` to fault a
parameter back in (parameter_sharder.cpp:242-271, LRU eviction 181-199).

On TPU the memory hierarchy is HBM <-> pinned host RAM, and the "fault in"
is a compiled H2D transfer XLA can overlap with compute. The mapping:

  reference                         this module
  ---------------------------------------------------------------
  register_parameter(name, ...)     plan_placement(params, config)
  max_resident_bytes budget         OffloadConfig.max_resident_bytes
  quantize_fp16_on_disk             OffloadConfig.offload_dtype="bfloat16"
                                    (bf16 is the TPU-idiomatic 16-bit type)
  require(name) disk->RAM load      fetch(...) inside the jitted step:
                                    jax.device_put back to "device" memory
  LRU eviction                      static largest-first spill plan (the
                                    whole step's working set is known at
                                    trace time — no runtime eviction needed)
  offload_all()                     apply_placement(...)
  owner_ptr nulling                 functional pytrees: the host copy IS the
                                    storage; nothing to null

Budget semantics are strict (test_sharder_strict.cpp analog): the PLANNED
resident set never exceeds `max_resident_bytes`. The reference must auto-raise
its budget to fit the largest single parameter (train_lora_gemma.cpp:434-441)
because `require()` materializes a param in the resident RAM pool; here a
fetched param is transient working set inside one XLA program, not a resident
pool entry, so no raise is needed — even a budget of 0 is valid (stream
everything).

Composes with FSDP: placement operates on whatever shardings you pass —
`NamedSharding.with_memory_kind("pinned_host")` keeps the partition spec, so
a parameter can be simultaneously FSDP-sharded across chips AND offloaded to
each chip's host RAM.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

HOST = "pinned_host"
DEVICE = "device"


@dataclasses.dataclass
class OffloadConfig:
    """Analog of ShardConfig (parameter_sharder.h:37-41)."""
    enable: bool = False
    max_resident_bytes: int = 0          # HBM budget for the planned tree
    offload_dtype: str = "bfloat16"      # "bfloat16" | "float32"
    min_offload_size: int = 2 ** 12      # tiny params never offloaded

    @property
    def np_offload_dtype(self):
        return jnp.bfloat16 if self.offload_dtype == "bfloat16" \
            else jnp.float32


def _leaf_bytes(x, dtype=None) -> int:
    d = np.dtype(dtype) if dtype is not None else \
        np.dtype(getattr(x, "dtype", np.float32))
    return int(np.prod(np.shape(x))) * d.itemsize


def plan_placement(params, config: OffloadConfig) -> Any:
    """Pytree of bool: True = offload this leaf to host RAM.

    Greedy largest-first spill: keep everything resident if it fits;
    otherwise offload the largest parameters until the resident set is
    under budget. Large weights amortize transfer latency best (XLA can
    overlap the H2D prefetch of layer i+1 with layer i's compute under
    lax.scan), so spilling big-first both meets the budget with the fewest
    transfers and hides them best — where the reference's LRU had to guess,
    the static plan knows the whole step's access pattern.
    """
    leaves, treedef = jax.tree.flatten(params)
    if not config.enable:
        return jax.tree.unflatten(treedef, [False] * len(leaves))
    sizes = [_leaf_bytes(x) for x in leaves]
    total = sum(sizes)
    budget = config.max_resident_bytes
    offload = [False] * len(leaves)
    resident = total
    order = sorted(range(len(leaves)), key=lambda i: -sizes[i])
    for i in order:
        if resident <= budget:
            break
        if sizes[i] < config.min_offload_size:
            continue
        offload[i] = True
        resident -= sizes[i]
    if resident > budget:
        import warnings
        warnings.warn(
            f"offload plan over budget: {resident} resident bytes > "
            f"{budget} budget — leaves below min_offload_size="
            f"{config.min_offload_size} alone exceed the budget",
            stacklevel=2)
    return jax.tree.unflatten(treedef, offload)


def placement_stats(params, plan, config: OffloadConfig) -> Dict[str, int]:
    """Resident/offloaded byte counts (reference's sharder stats report)."""
    resident = offloaded = 0
    for x, off in zip(jax.tree.leaves(params), jax.tree.leaves(plan)):
        if off:
            offloaded += _leaf_bytes(x, config.np_offload_dtype)
        else:
            resident += _leaf_bytes(x)
    return {"resident_bytes": resident, "offloaded_bytes": offloaded,
            "n_offloaded": sum(map(bool, jax.tree.leaves(plan)))}


def apply_placement(params, plan, shardings, config: OffloadConfig):
    """Place the tree: offloaded leaves -> host memory in offload_dtype,
    resident leaves -> their given sharding unchanged.

    `shardings` is a pytree of jax.sharding.Sharding (e.g. from
    parallel.mesh.params_shardings) or a single sharding applied to all.
    """
    if not isinstance(shardings, (dict, list, tuple)):
        shardings = jax.tree.map(lambda _: shardings, params)
    od = config.np_offload_dtype

    def place(x, off, sh):
        x = jnp.asarray(x)
        if off:
            return jax.device_put(x.astype(od),
                                  sh.with_memory_kind(HOST))
        return jax.device_put(x, sh)

    return jax.tree.map(place, params, plan, shardings)


def fetch(params, plan, shardings, compute_dtype=None):
    """The `require()` analog, usable INSIDE jit: move offloaded leaves back
    to device memory (and optionally cast). Under jit this lowers to H2D
    copies that XLA schedules/overlaps; outside jit it is an eager transfer.
    """
    if not isinstance(shardings, (dict, list, tuple)):
        shardings = jax.tree.map(lambda _: shardings, params)

    def pull(x, off, sh):
        if off:
            x = jax.device_put(x, sh.with_memory_kind(DEVICE))
        if compute_dtype is not None and jnp.issubdtype(x.dtype,
                                                        jnp.floating):
            x = x.astype(compute_dtype)
        return x

    return jax.tree.map(pull, params, plan, shardings)
