"""Multi-host (multi-process) distributed backend.

The reference is strictly single-process (SURVEY.md §2.11: no NCCL/MPI/
sockets anywhere); its only "scale-out" axis is gradient accumulation. The
TPU-native rebuild gets real scale-out from XLA's compiled collectives, and
this module supplies the process-level runtime around them:

  * `initialize(...)` — bring up the JAX distributed service
    (`jax.distributed.initialize`), which wires the coordination service +
    per-host device visibility. On TPU pods every argument is auto-detected
    from the metadata environment; off-pod (CPU/GPU fleets or explicit
    testing) the coordinator address / process count / process id come from
    flags or the standard `JAX_COORDINATOR_ADDRESS` / `JAX_NUM_PROCESSES` /
    `JAX_PROCESS_ID` environment variables.
  * `make_hybrid_mesh(...)` — a ("data", "fsdp") mesh laid out so the
    "fsdp" axis (param all-gathers / grad reduce-scatters every step) rides
    ICI inside each host's slice, and the "data" axis (one grad all-reduce
    per step) crosses the DCN host boundary — the standard
    bandwidth-hierarchy-aware layout (scaling-book recipe; built on
    `mesh_utils.create_hybrid_device_mesh`).
  * `device_put_global(...)` / `put_batch_global(...)` — multi-host batch
    feeding. Under multi-host jit every argument must be a global
    `jax.Array` spanning all processes; `jax.device_put` of host numpy
    cannot produce one. Each process runs the SAME seeded data pipeline
    (identical global batch everywhere — WikiText-2 is small and
    tokenization is cheap/pretokenizable), and
    `jax.make_array_from_callback` slices out exactly the shards addressable
    from this process. No cross-host data exchange ever happens on the
    input path — which is also what makes the async prefetcher
    (data/prefetch.py) multi-host safe: placement is collective-free, so
    issuing batch k+1's put while step k computes needs no cross-process
    coordination, and every process's background producer yields the same
    seeded sequence.

Single-process runs (including every test and the tunneled single-chip
bench) pass through all of this untouched: `initialize` is a no-op without
a multi-process request, and `device_put_global` degrades to a plain
sharded device_put.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from mobilefinetuner_tpu.core.logging import get_logger

log = get_logger()

_INITIALIZED = False


def env_int(name: str) -> Optional[int]:
    v = os.environ.get(name, "")
    return int(v) if v else None


def initialize(coordinator: str = "", num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               force: bool = False, connect_retries: int = 4,
               connect_backoff_s: float = 1.0,
               connect_backoff_cap_s: float = 10.0) -> bool:
    """Start the JAX distributed runtime when a multi-process run is
    requested; returns True iff it was (or already had been) started.

    Resolution order per field: explicit argument > JAX_* env var > TPU-pod
    auto-detection (passing None lets jax probe the pod metadata server).
    `force=True` (the --multihost flag) starts the runtime even with no
    explicit addressing — the TPU-pod case, where every argument is
    auto-detected; off-pod, a failed auto-detection degrades to
    single-process with a warning instead of crashing, so the same command
    line works on a pod and on a dev box.

    A plain single-process invocation (no flag, no env, pod size 1) is a
    no-op so the CLI entry points never hang waiting for phantom peers.

    Explicitly-addressed connections RETRY with capped exponential
    backoff (`connect_retries`/`connect_backoff_s`) before failing: at
    fleet-restart time — exactly when the elastic controller relaunches
    everything at once — the workers race the coordinator coming back
    up, and failing fast on that race turns one recovered host into a
    second fleet restart. Every attempt is logged; after the budget the
    ORIGINAL error raises (not a wrapper), so the operator sees the
    real failure, not the retry machinery.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    num_processes = num_processes if num_processes is not None \
        else env_int("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None \
        else env_int("JAX_PROCESS_ID")
    want = force or bool(coordinator) or (num_processes or 1) > 1
    if not want:
        return False
    explicit = bool(coordinator) or (num_processes or 1) > 1
    budget = max(connect_retries, 0) if explicit else 0
    first_err: Optional[BaseException] = None
    for attempt in range(budget + 1):
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator or None,
                num_processes=num_processes, process_id=process_id)
            break
        except Exception as e:
            if not explicit:
                # --multihost with nothing to address: degrade to
                # single-process (dev-box behavior), never retry
                log.warning(f"--multihost: auto-detection failed ({e}); "
                            f"continuing single-process")
                return False
            first_err = first_err or e
            if attempt >= budget:
                raise first_err
            delay = min(connect_backoff_s * (2 ** attempt),
                        connect_backoff_cap_s)
            log.warning(
                f"distributed: coordinator connect attempt "
                f"{attempt + 1}/{budget + 1} failed "
                f"({type(e).__name__}: {e}); retrying in {delay:.1f}s")
            time.sleep(delay)
    _INITIALIZED = True
    log.info(f"distributed: process {jax.process_index()}"
             f"/{jax.process_count()} up, "
             f"{len(jax.local_devices())} local / "
             f"{len(jax.devices())} global devices")
    return True


def is_coordinator() -> bool:
    """True on the process that owns logging/checkpoint writes."""
    return jax.process_index() == 0


def make_hybrid_mesh(data: int = 1, fsdp: Optional[int] = None) -> Mesh:
    """("data", "fsdp") mesh over ALL processes' devices, DCN-aware.

    Layout rule: the fsdp axis is packed within each host's ICI domain
    (param all-gather + grad reduce-scatter are the per-step bandwidth
    hogs), and the data axis absorbs the cross-host DCN dimension (its
    only per-step collective is one gradient all-reduce). Concretely, with
    P processes × L local devices and a request (data=D, fsdp=F):

      * F must fit in one host's slice (F divides L): fsdp lives on ICI.
      * D = (L//F per host) × P: the data axis spans hosts.

    Requests that cannot honor the hierarchy (F > L) fall back to
    `mesh_utils.create_device_mesh`'s global layout with a warning rather
    than failing — correctness never depends on the layout, only the
    collective latency does.

    Single-process: equivalent to parallel.mesh.make_mesh (same axis
    names, same shapes), so downstream sharding code cannot tell the
    difference.
    """
    from jax.experimental import mesh_utils

    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    n_proc = jax.process_count()
    if fsdp is None or fsdp == 0:
        if n_global % data != 0:
            raise ValueError(f"{n_global} devices not divisible by "
                             f"data={data}")
        fsdp = n_global // data
    if data * fsdp != n_global:
        raise ValueError(
            f"data*fsdp={data * fsdp} != global devices={n_global}")
    if n_proc == 1:
        devices = mesh_utils.create_device_mesh((data, fsdp))
        return Mesh(devices, axis_names=("data", "fsdp"))
    if n_local % fsdp == 0:
        # fsdp within a host (ICI), data = local remainder × processes (DCN)
        ici_data = n_local // fsdp
        try:
            devices = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=(ici_data, fsdp),
                dcn_mesh_shape=(n_proc, 1))
        except ValueError:
            # Platforms without slice_index granules (CPU multi-process
            # testing): group by process_index by hand — the data axis is
            # process-major, so fsdp still never crosses a process.
            by_proc = {}
            for d in sorted(jax.devices(), key=lambda d: (d.process_index,
                                                          d.id)):
                by_proc.setdefault(d.process_index, []).append(d)
            rows = [np.asarray(ds).reshape(ici_data, fsdp)
                    for _, ds in sorted(by_proc.items())]
            devices = np.concatenate(rows, axis=0)
        return Mesh(devices, axis_names=("data", "fsdp"))
    log.warning(
        f"fsdp={fsdp} does not fit one host's {n_local} local devices; "
        f"fsdp collectives will cross DCN (slower, still correct)")
    devices = mesh_utils.create_device_mesh((data, fsdp))
    return Mesh(devices, axis_names=("data", "fsdp"))


def device_put_global(x, sharding) -> jax.Array:
    """device_put that also works when `sharding` spans processes this
    host cannot address (multi-host jit inputs must be global jax.Arrays;
    plain device_put of host data cannot build one). `x` must hold the
    same global value on every process — true for checkpoint loads (every
    host reads the same file), the seeded data pipeline, and step-folded
    dropout keys. Single-process this is exactly device_put — device-
    resident leaves are NOT synced to host."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    x = np.asarray(x)  # multi-process only: feed shards from a host copy
    return jax.make_array_from_callback(x.shape, sharding,
                                        lambda idx: x[idx])


def put_batch_global(batch: dict, sharding_for) -> dict:
    """One placement pass over a batch dict: `sharding_for(key)` names
    each leaf's sharding, `device_put_global` makes the transfer (global
    under multi-host, plain async device_put single-process). This is the
    shard-aware placement primitive behind `mesh.shard_batch` and the
    input pipeline's lookahead placer (`mesh.make_batch_placer`)."""
    return {k: device_put_global(v, sharding_for(k))
            for k, v in batch.items()}


def allgather_scalars(value: float) -> list:
    """Every process's copy of a host-side scalar, as a plain list indexed
    by process: the straggler-attribution primitive (cli/common.py feeds
    each host's measured per-step wall time through on the
    --straggler_cadence boundary, and the coordinator compares the fleet).
    COLLECTIVE under multi-process — every process must call it at the
    same step, which the deterministic cadence guarantees. Single-process:
    [value], no device work at all, so the single-host path costs
    nothing."""
    if jax.process_count() == 1:
        return [float(value)]
    from jax.experimental import multihost_utils
    out = multihost_utils.process_allgather(
        np.asarray([value], np.float32))
    return [float(v) for v in np.asarray(out).reshape(-1)]


def gather_to_host(tree):
    """Bring a (possibly cross-process-sharded) pytree fully to host for
    checkpoint writing. COLLECTIVE under multi-process: every process must
    call it (process_allgather runs a psum under the hood); afterwards
    only the coordinator needs to write the result. Single-process:
    returns the tree unchanged (savers device_get as usual)."""
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    def pull(x):
        if not isinstance(x, jax.Array):
            return x
        if x.is_fully_addressable or x.is_fully_replicated:
            return np.asarray(x)
        return multihost_utils.process_allgather(x, tiled=True)

    return jax.tree.map(pull, tree)
