"""Preemption drain: turn SIGTERM/SIGINT into an orderly last-step save.

The reference's whole loop is built to be interrupted — the energy
governor suspends training on battery/thermal signals and the app
lifecycle can kill the process at any time (PAPER.md); the TPU-fleet
analog is the preemption notice. Without a handler, SIGTERM kills the
run wherever it happens to be: the steps since the last periodic save
are lost, the telemetry stream ends truncated, and the fleet controller
cannot tell a preemption from a crash.

`PreemptionGuard` converts the signal into a *per-step drain flag* that
`cli/common.run_training` checks at every step boundary: the step in
flight completes, the metrics buffer flushes, one final atomic
checkpoint lands (through the existing `AsyncCheckpointer` — the drain
blocks until the write is durable), the stream ends with a schema-valid
`run_end` carrying `exit="preempted"`/`reason="preempted"`, and the
process exits with `EXIT_PREEMPTED` — a DISTINCT, resumable exit code
the fleet controller (tools/fleet_controller.py) recognizes as "clean
drain, resume me" rather than "crashed, count against the restart
budget". A preemption notice therefore costs one step plus one drain
instead of a lost run (DESIGN.md §18).

The serve loop consumes the same guard (round 14, DESIGN.md §19):
`serve/engine.ServeEngine.install_preemption()` observes the flag at
decode-step boundaries — admissions stop, the queued remainder rejects
with `reason="shutdown"`, in-flight requests finish, and close()
records the same `run_end{exit=preempted, reason=preempted}` contract,
so a drained server and a drained trainer are indistinguishable to the
recovery layer.

A SECOND signal during the drain aborts it (KeyboardInterrupt): the
operator — or the platform's hard-kill escalation — always wins over a
wedged save.
"""

from __future__ import annotations

import signal
from typing import Dict, Optional, Tuple

# EX_TEMPFAIL: "temporary failure, retry later" — the resumable-exit
# contract shared by run_training's drain path, the simulated fleet
# workers (tools/multihost_smoke.py --sim_worker), and the controller's
# restart policy. Distinct from the watchdog's abort (113 = wedged, the
# host needs a restart) and from ordinary crashes (count against the
# restart budget).
EXIT_PREEMPTED = 75


class PreemptionGuard:
    """Signal handler -> drain flag (installed only on the main thread —
    Python restricts `signal.signal` to it; elsewhere `install()` leaves
    `installed` False and the caller degrades to default signal
    behavior). `uninstall()` restores the previous handlers so repeated
    in-process runs (tests, notebooks) never leak handler state."""

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self.signals = signals
        self.triggered = False
        self.signal_name: Optional[str] = None
        self.installed = False
        self._prev: Dict[int, object] = {}

    def _handler(self, signum, frame):
        if self.triggered:
            # a second signal mid-drain: stop draining NOW — the
            # operator (or the platform's kill escalation) outranks a
            # slow final save
            raise KeyboardInterrupt(
                f"second {signal.Signals(signum).name} during drain")
        self.triggered = True
        self.signal_name = signal.Signals(signum).name

    def install(self) -> "PreemptionGuard":
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handler)
            self.installed = True
        except ValueError:
            # not the main thread (embedded runs): restore whatever we
            # managed to install and report unavailable
            self.uninstall()
        return self

    def uninstall(self) -> None:
        for s, h in self._prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, TypeError, OSError):
                pass
        self._prev.clear()
        self.installed = False
