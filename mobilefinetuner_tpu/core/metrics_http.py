"""Live OpenMetrics endpoint over the telemetry emit path (DESIGN.md §22).

Production fleets are watched by scrapers, not by tailing JSONL: this
module turns the run's own event stream into a Prometheus/OpenMetrics
`/metrics` endpoint plus a `/healthz` probe, WITHOUT a second
instrumentation layer — `MetricsRegistry.observe` attaches as a
`Telemetry` observer (core/telemetry.py `add_observer`), so every
number a scraper reads came through the exact emit call the JSONL sink
wrote. One measurement, three consumers (stream, report tools,
scraper); nothing here can drift from the stream because nothing here
measures anything.

Zero-sync invariant, extended: this module NEVER imports jax and never
touches a device — it folds host-side floats that already exist into
counters/gauges/histograms under its own lock (tests pin the no-jax
rule structurally). A scrape can therefore never add a retrace or a
device sync to the hot path it observes.

Server: stdlib ThreadingHTTPServer on a daemon thread, bound to
127.0.0.1 by default — the endpoint exposes operational detail (paths,
config, loss curves), so exposing it beyond the host is an explicit
`--metrics_addr 0.0.0.0` decision, not a default. `port=0` binds an
ephemeral port (the `port` property reports it; tests use this), the
CLI flags treat 0 as "off".

Exposition format: OpenMetrics text (the `# TYPE` blocks, counters
with the `_total` suffix, terminated by `# EOF`), served with the
OpenMetrics content type. Prometheus scrapes it as-is.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"

# lock-discipline declaration (core/static_checks.py, DESIGN.md §24):
# observe() runs under the Telemetry emit lock on whatever thread
# emitted; render()/health() on HTTP handler threads — the registry's
# own _lock serializes them, and graftlint checks every access.
GRAFT_SHARED_STATE = {
    "MetricsRegistry": {
        "lock": "_lock",
        "guarded": ["_counters", "_gauges", "_hists", "_last_rec_t",
                    "_last_step", "_last_exit", "observed"],
        # fold helpers assert-by-convention the caller holds _lock;
        # graftlint flags any call site outside a with-lock block
        "locked_helpers": ["_count", "_count_to", "_gauge", "_hist"],
        "channels": [],
        "note": "Histogram instances are reachable only via _hists, so "
                "their fields inherit the registry lock",
    },
}

# default histogram bucket edges (ms): wide enough for a 20 ms LoRA
# step and a 2 s governor-throttled one, for TTFT under load and for
# checkpoint writes — one ladder, log-spaced
_MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
               1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


def _fmt_val(v: float) -> str:
    """OpenMetrics float rendering: integers without the trailing .0
    noise, everything finite as repr (full precision round-trips)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class Histogram:
    """Fixed-bucket cumulative histogram (the OpenMetrics shape)."""

    def __init__(self, buckets=_MS_BUCKETS):
        self.edges = tuple(sorted(buckets))
        self.counts = [0] * (len(self.edges) + 1)  # +1: the +Inf bucket
        self.total = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.total += 1
        self.sum += v
        for i, edge in enumerate(self.edges):
            if v <= edge:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self, name: str) -> List[str]:
        lines = [f"# TYPE {name} histogram"]
        cum = 0
        for edge, c in zip(self.edges, self.counts):
            cum += c
            lines.append(f'{name}_bucket{{le="{_fmt_val(edge)}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {self.total}')
        lines.append(f"{name}_count {self.total}")
        lines.append(f"{name}_sum {_fmt_val(round(self.sum, 6))}")
        return lines


class MetricsRegistry:
    """Event records in, OpenMetrics text out.

    `observe(rec)` dispatches on `rec["event"]` and folds the payload
    into counters (monotonic, `_total`-suffixed), gauges (last value
    wins; None clears), and histograms (step time, TTFT, TPOT). All
    metric names carry the `mft_` prefix. Unknown event types are
    ignored — the registry must keep working as the taxonomy grows.

    Thread-safe: `observe` runs under the Telemetry emit lock on
    whatever thread emitted (step loop, checkpoint writer, watchdog),
    `render`/`health` on HTTP handler threads — one internal lock
    serializes them all.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, tuple], float] = {}
        self._gauges: Dict[Tuple[str, tuple], Optional[float]] = {}
        self._hists: Dict[str, Histogram] = {}
        self._last_rec_t: Optional[float] = None
        self._last_step: Optional[int] = None
        self._last_exit: Optional[str] = None
        self.observed = 0  # records seen (test observable)

    # -- folding helpers (call under self._lock) -----------------------------

    def _count(self, name: str, inc: float = 1.0, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        self._counters[key] = self._counters.get(key, 0.0) + inc

    def _count_to(self, name: str, value: float, **labels) -> None:
        """Monotonic set-to-max (step counters arrive as absolutes; a
        rollback rewinds the loop step but a counter may never go
        down)."""
        key = (name, tuple(sorted(labels.items())))
        self._counters[key] = max(self._counters.get(key, 0.0), value)

    def _gauge(self, name: str, value, **labels) -> None:
        key = (name, tuple(sorted(labels.items())))
        self._gauges[key] = None if value is None else float(value)

    def _hist(self, name: str, value) -> None:
        if value is None:
            return
        self._hists.setdefault(name, Histogram()).observe(float(value))

    # -- the observer ---------------------------------------------------------

    def observe(self, rec: dict) -> None:
        if not isinstance(rec, dict):
            return
        ev = rec.get("event")
        g = rec.get
        with self._lock:
            self.observed += 1
            self._last_rec_t = time.time()
            if isinstance(g("step"), int):
                self._last_step = g("step")
            if ev == "step_stats":
                self._count_to("mft_steps", g("step") or 0)
                self._hist("mft_step_time_ms", g("step_time_ms"))
                for f in ("loss", "ema", "lr", "grad_norm", "tok_s",
                          "mfu", "host_wait_ms", "hbm_mb", "queue_depth",
                          "param_norm", "update_ratio"):
                    self._gauge(f"mft_{f}", g(f))
                if g("skipped"):
                    self._count("mft_skipped_steps", g("skipped"))
            elif ev == "request":
                self._count("mft_requests", phase=g("phase", "?"))
                if g("phase") == "finish":
                    self._hist("mft_ttft_ms", g("ttft_ms"))
                    self._hist("mft_tpot_ms", g("tpot_ms"))
                    self._hist("mft_queue_ms", g("queue_ms"))
                    if g("new_tokens"):
                        self._count("mft_generated_tokens",
                                    g("new_tokens"))
            elif ev == "serve_stats":
                for f in ("queue_depth", "active", "occupancy",
                          "free_blocks", "p95_step_ms", "hbm_mb",
                          "pool_mb",
                          # round-22 cache vitals: the r21 counters the
                          # registry used to drop — the router's
                          # affinity scoring and the fleet report read
                          # them off /metrics, not the JSONL
                          "prefix_hit_rate", "cow_copies",
                          "blocks_in_use"):
                    self._gauge(f"mft_serve_{f}", g(f))
                # page-pool occupancy: fraction of allocatable pages
                # held by live requests (parked cache pages count free)
                in_use, free = g("blocks_in_use"), g("free_blocks")
                if isinstance(in_use, (int, float)) \
                        and isinstance(free, (int, float)) \
                        and in_use + free > 0:
                    self._gauge("mft_serve_pool_occupancy",
                                round(in_use / (in_use + free), 4))
                self._count_to("mft_decode_steps", g("step") or 0)
                for s in ("finished", "cancelled", "rejected", "timeout",
                          "error"):
                    if isinstance(g(s), int):
                        self._count_to("mft_serve_terminal", g(s),
                                       state=s)
            elif ev == "route":
                # round-22 router decisions: the histogram over
                # (policy, replica) IS the routing-decision report, and
                # scrape age tells the operator how stale the snapshots
                # behind those decisions ran
                self._count("mft_route_decisions",
                            policy=g("policy", "?"),
                            replica=str(g("replica")))
                self._hist("mft_route_scrape_age_ms",
                           g("scrape_age_ms"))
            elif ev == "anomaly":
                self._count("mft_anomalies", kind=g("kind", "?"))
            elif ev == "throttle":
                self._count("mft_throttle_decisions")
            elif ev == "straggler":
                self._count("mft_stragglers")
            elif ev == "hang":
                self._count("mft_hangs")
            elif ev == "checkpoint":
                self._count("mft_checkpoints")
                self._hist("mft_ckpt_write_ms", g("write_ms"))
                if g("bytes"):
                    self._count("mft_ckpt_bytes", g("bytes"))
            elif ev == "ckpt_dropped":
                self._count("mft_ckpt_dropped")
            elif ev == "rollback":
                self._count("mft_rollbacks",
                            ok=str(bool(g("ok"))).lower())
            elif ev == "degrade":
                self._count("mft_degrades", rung=g("rung", "?"))
            elif ev == "mem_check":
                self._gauge("mft_mem_est_mb", g("est_mb"))
                self._gauge("mft_mem_cap_mb", g("cap_mb"))
                if g("verdict") == "over":
                    self._count("mft_mem_over")
            elif ev == "ckpt_verify":
                self._count("mft_ckpt_verify",
                            ok=str(bool(g("ok"))).lower())
            elif ev == "profile_capture":
                self._count("mft_profile_captures",
                            trigger=g("trigger", "?"))
            elif ev == "eval":
                self._gauge("mft_eval_loss", g("loss"))
                self._gauge("mft_eval_ppl", g("ppl"))
            elif ev == "compile":
                self._count("mft_compiles")
                self._gauge("mft_compile_peak_hbm_mb", g("peak_hbm_mb"))
            elif ev == "preempt":
                self._count("mft_preempts")
            elif ev == "run":
                # round-23 run registry (core/run_registry.py): count
                # finalized registrations by kind/terminal status —
                # start records are in-flight, not a terminal tally
                if g("phase") == "end":
                    self._count("mft_registered_runs",
                                kind=g("kind", "?"),
                                status=g("status", "?"))
            elif ev == "trend":
                # round-23 longitudinal sentinel (tools/observatory.py):
                # the newest sample, its rolling median and robust z per
                # gated series — a dashboard reads the regression story
                # off the SAME record the verdict JSON carries
                labels = dict(metric=g("metric", "?"),
                              config=g("config", "?"),
                              platform=g("platform", "?"))
                self._gauge("mft_trend_value", g("value"), **labels)
                self._gauge("mft_trend_median", g("median"), **labels)
                self._gauge("mft_trend_z", g("z"), **labels)
                if g("regressed"):
                    self._count("mft_trend_regressions", **labels)
            elif ev == "run_end":
                self._count("mft_runs", exit=g("exit", "?"))
                self._last_exit = g("exit")
                gp = g("goodput") or {}
                if isinstance(gp, dict) and "productive_frac" in gp:
                    self._gauge("mft_goodput_productive_frac",
                                gp.get("productive_frac"))
                    for k, v in gp.items():
                        if k.endswith("_s") and k != "total_s":
                            self._gauge("mft_goodput_seconds",
                                        v, bucket=k[:-2])

    def set_gauge(self, name: str, value, **labels) -> None:
        """Public labeled-gauge setter for numbers that do NOT arrive
        through the telemetry emit path — the round-22 router folds
        each replica's scraped vitals in as
        `mft_fleet_*{replica="k"}` gauges (None clears)."""
        with self._lock:
            self._gauge(name, value, **labels)

    def observe_hist(self, name: str, value) -> None:
        """Public histogram feed for the same out-of-band callers
        (router-side TTFT/TPOT/queue-wait over collected results)."""
        with self._lock:
            self._hist(name, value)

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        """Public labeled-counter increment for out-of-band callers
        (router-side fleet request terminals by state)."""
        with self._lock:
            self._count(name, value, **labels)

    # -- exposition -----------------------------------------------------------

    def render(self) -> str:
        """The /metrics body: one `# TYPE` block per metric family,
        `# EOF` terminated (the OpenMetrics framing scrapers check)."""
        with self._lock:
            lines: List[str] = []
            for name in sorted({n for (n, _l) in self._counters}):
                lines.append(f"# TYPE {name} counter")
                for (n, labels), v in sorted(self._counters.items()):
                    if n == name:
                        lines.append(
                            f"{name}_total{_labels_str(labels)} "
                            f"{_fmt_val(v)}")
            for name in sorted({n for (n, _l) in self._gauges}):
                samples = [(labels, v) for (n, labels), v
                           in sorted(self._gauges.items())
                           if n == name and v is not None]
                if not samples:
                    continue
                lines.append(f"# TYPE {name} gauge")
                for labels, v in samples:
                    lines.append(
                        f"{name}{_labels_str(labels)} {_fmt_val(v)}")
            for name in sorted(self._hists):
                lines.extend(self._hists[name].render(name))
            lines.append("# EOF")
            return "\n".join(lines) + "\n"

    def health(self) -> dict:
        """Generic /healthz payload for entry points without a richer
        health source (the serve engine passes its own health())."""
        with self._lock:
            now = time.time()
            return {
                "status": "ok",
                "last_step": self._last_step,
                "last_event_age_s": (round(now - self._last_rec_t, 3)
                                     if self._last_rec_t else None),
                "events_observed": self.observed,
                "last_exit": self._last_exit,
            }


class MetricsServer:
    """ThreadingHTTPServer wrapper: /metrics (OpenMetrics), /healthz
    (JSON from `health_fn`), plus optional JSON `routes` — the round-22
    serve-fleet data plane (a replica's /submit and /collect) rides
    the same server instead of opening a second port. Daemon threads
    throughout — a live scrape can never hold the process open past
    the run.

    `routes`: {path: fn(payload) -> (code, obj)} — fn receives the
    parsed JSON body on POST (None on GET) and returns an HTTP status
    plus a JSON-serializable object. Route exceptions surface as 500s,
    same as a scrape bug."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 addr: str = "127.0.0.1",
                 health_fn: Optional[Callable[[], dict]] = None,
                 routes: Optional[Dict[str, Callable]] = None):
        self.registry = registry
        self._health_fn = health_fn or registry.health
        self._routes = dict(routes or {})
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, code, ctype, body):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _dispatch(self, payload):
                path = self.path.split("?")[0]
                try:
                    if path == "/metrics" and payload is None:
                        return self._respond(
                            200, OPENMETRICS_CONTENT_TYPE,
                            server.registry.render().encode())
                    if path == "/healthz" and payload is None:
                        h = server._health_fn()
                        code = 200 if h.get("status", "ok") == "ok" \
                            else 503
                        return self._respond(
                            code, "application/json",
                            (json.dumps(h) + "\n").encode())
                    fn = server._routes.get(path)
                    if fn is None:
                        return self._respond(404, "text/plain",
                                             b"not found\n")
                    code, obj = fn(payload)
                    body = (json.dumps(obj) + "\n").encode()
                    return self._respond(code, "application/json", body)
                except Exception as e:  # a scrape bug must stay a 500
                    return self._respond(
                        500, "text/plain",
                        f"error: {type(e).__name__}\n".encode())

            def do_GET(self):  # noqa: N802 — stdlib API
                self._dispatch(None)

            def do_POST(self):  # noqa: N802 — stdlib API
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, UnicodeDecodeError):
                    return self._respond(400, "text/plain",
                                         b"bad json\n")
                self._dispatch(payload)

            def log_message(self, *a):  # scrapes are not log lines
                pass

        self._httpd = ThreadingHTTPServer((addr, max(port, 0)), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="metrics-http", daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        """The BOUND port (differs from the requested one under
        port=0 — ephemeral bind, the test path)."""
        return self._httpd.server_address[1]

    @property
    def addr(self) -> str:
        return self._httpd.server_address[0]

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=2.0)


def start_metrics(telemetry, port: int, addr: str = "127.0.0.1",
                  health_fn: Optional[Callable[[], dict]] = None
                  ) -> Optional[MetricsServer]:
    """The one-call wiring every entry point uses: build a registry,
    attach it as a telemetry observer, serve it. Returns None when
    `port` is falsy/negative (the CLI's 0 = off convention; tests that
    want an ephemeral bind construct MetricsServer directly)."""
    if not port or port < 0:
        return None
    registry = MetricsRegistry()
    telemetry.add_observer(registry.observe)
    return MetricsServer(registry, port=port, addr=addr,
                         health_fn=health_fn)
