"""Unified run-telemetry: append-only JSONL event stream, loss-spike
detection, and the shared FLOP/MFU accounting.

The reference ships a real observability stack for its mobile loop —
leveled Logger, CSV MetricsLogger, the RSS/performance monitors
(performance_monitor.h), and the energy telemetry feeding the throttler
(power_monitor.cpp) — but none of it is machine-readable per RUN: there
is no record of why a step was slow, whether the run stayed healthy, or
what fraction of peak FLOPs it achieved. This module is the TPU
rebuild's answer: one run-scoped, crash-durable JSONL stream that every
training/eval entry point writes through, with a fixed event taxonomy
(`EVENT_SCHEMA`) that tools/telemetry_report.py and
tests/test_telemetry.py both validate against, so the contract cannot
drift from the implementation.

Design rules (DESIGN.md §13):
  - coordinator-only sink: under multi-host every process computes the
    same metrics, but only process 0 writes (same rule as the CSV/JSONL
    sinks in cli/common.run_training);
  - crash-durable: every event is written and flushed individually, so
    a killed run keeps everything up to its last completed flush; a
    resumed run APPENDS to the same stream, continuing the monotonic
    `seq` from the last valid line (a truncated tail line — the process
    died mid-write — is skipped, not fatal);
  - zero-sync invariant: nothing here touches the device. On-device
    health metrics (train/trainer.py param_norm, update_ratio,
    nonfinite_count) ride the step loop's existing buffered-metrics
    device_get; telemetry only formats what that single fetch returned.

MFU accounting lives here — `transformer_flops` was lifted OUT of
bench.py (which now imports it) so the benchmark's MFU column and the
in-loop `step_stats.mfu` agree by construction
(tests/test_bench_contract.py pins the identity).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Any, Callable, Dict, Optional

# --------------------------- event taxonomy ---------------------------------

_NUM = (int, float)
_OPT_NUM = (int, float, type(None))
_OPT_STR = (str, type(None))

# Per-event required payload fields and their allowed types. Every event
# additionally carries the envelope: event (str), seq (int, monotonic per
# stream), t (float unix time). Extra fields are ALLOWED (the schema is a
# floor, not a ceiling) so events can grow without breaking old readers.
EVENT_SCHEMA: Dict[str, Dict[str, tuple]] = {
    # one per run, always the stream's first event of that run
    "run_start": {
        "jax_version": (str,),
        "mesh_shape": (dict, type(None)),
        "process_count": (int,),
        "process_index": (int,),
        "device_kind": (str,),
        "device_count": (int,),
        "config": (dict,),
    },
    # one per compiled executable (wall time + XLA's own FLOP count +
    # compiled-peak HBM from the memory analysis)
    "compile": {
        "step": (int,),
        "wall_s": _NUM,
        "flops": _OPT_NUM,          # compiled.cost_analysis(); None if n/a
        "peak_hbm_mb": _OPT_NUM,
    },
    # periodic, one per metrics flush (interval-averaged timings; the
    # loss/health fields are the interval's LAST step). loss/ema are
    # null exactly when the value was non-finite (strict-JSON rule).
    "step_stats": {
        "step": (int,),
        "loss": _OPT_NUM,
        "ema": _OPT_NUM,
        "lr": _NUM,
        "grad_norm": _OPT_NUM,
        "step_time_ms": _NUM,
        "host_wait_ms": _NUM,
        "slept_ms": _OPT_NUM,       # governor sleep inside the interval
        "tok_s": _NUM,
        "mfu": _OPT_NUM,            # None when peak FLOPs unknown (CPU)
        "param_norm": _OPT_NUM,     # None on step builders without the
        "update_ratio": _OPT_NUM,   # on-device health metrics
        "nonfinite_count": _OPT_NUM,
        "hbm_mb": _NUM,
        "queue_depth": _OPT_NUM,    # input-pipeline gauge (None: no stream)
    },
    # governor throttle decision (system/governor.py event_sink)
    "throttle": {
        "step": (int,),
        "sleep_ms": _NUM,
        "battery": _OPT_NUM,
        "temp": _OPT_NUM,
        "source": (str,),           # "schedule" | "telemetry"
    },
    # host-side loss-spike / divergence detector fired (loss is null
    # exactly for kind=nonfinite_loss — strict-JSON rule)
    "anomaly": {
        "step": (int,),
        "kind": (str,),             # "loss_spike" | "nonfinite_loss"
        "loss": _OPT_NUM,
        "ema": _OPT_NUM,
        "zscore": _OPT_NUM,
    },
    # loss/ppl are null for evals that aren't NLL-shaped (eval_mmlu
    # reports macro_accuracy/micro_accuracy as extra fields instead)
    "eval": {
        "step": (int,),
        "loss": _OPT_NUM,
        "ppl": _OPT_NUM,
        "tokens": (int,),
    },
    "checkpoint": {
        "step": (int,),
        "final": (bool,),
        "wall_s": _NUM,
    },
    # one per run on orderly exit; exit != "ok" names the exception type
    "run_end": {
        "steps": (int,),
        "wall_s": _NUM,
        "exit": (str,),
    },
}


def validate_event(rec: Any) -> Optional[str]:
    """None if `rec` satisfies the contract, else a human-readable reason.
    Shared by tests/test_telemetry.py and tools/telemetry_report.py so the
    validator cannot fork from the schema."""
    if not isinstance(rec, dict):
        return f"not an object: {type(rec).__name__}"
    ev = rec.get("event")
    if ev not in EVENT_SCHEMA:
        return f"unknown event type: {ev!r}"
    if not isinstance(rec.get("seq"), int) or rec["seq"] < 0:
        return f"{ev}: bad seq {rec.get('seq')!r}"
    if not isinstance(rec.get("t"), (int, float)):
        return f"{ev}: bad t {rec.get('t')!r}"
    for field, types in EVENT_SCHEMA[ev].items():
        if field not in rec:
            return f"{ev}: missing field {field!r}"
        v = rec[field]
        # bool is an int subclass; reject it where a number is expected
        if isinstance(v, bool) and bool not in types:
            return f"{ev}.{field}: bool where {types} expected"
        if not isinstance(v, types):
            return f"{ev}.{field}: {type(v).__name__} not in {types}"
    return None


# --------------------------- the JSONL sink ---------------------------------

def _last_seq(path: str) -> int:
    """Highest seq among the file's valid JSONL lines (-1 when none).
    Scans the whole file: it is read once at open, and a telemetry stream
    is small (one step_stats per flush, not per step)."""
    last = -1
    try:
        with open(path, "rb") as f:
            for raw in f:
                try:
                    rec = json.loads(raw)
                    s = rec.get("seq")
                    if isinstance(s, int):
                        last = max(last, s)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue  # truncated tail line from a crashed writer
    except OSError:
        return -1
    return last


def _json_finite(v):
    """Replace non-finite floats (recursively) with None so every
    emitted line is strict RFC 8259 JSON."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    if isinstance(v, dict):
        return {k: _json_finite(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_finite(x) for x in v]
    return v


class Telemetry:
    """Append-only JSONL event stream, one record per `emit` call.

    A falsy `path` (or enabled=False — how non-coordinator processes are
    muted) makes every method a no-op, so call sites never branch.
    Appending to an existing file continues its seq numbering — the
    crash/resume contract: one stream per run directory, ordered across
    process restarts.
    """

    def __init__(self, path: str = "", enabled: bool = True):
        self.path = path
        self.enabled = bool(path) and enabled
        self._f = None
        self._seq = 0
        if self.enabled:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            if os.path.exists(path):
                self._seq = _last_seq(path) + 1
            self._f = open(path, "a", encoding="utf-8")
            # a killed writer can leave a partial line with NO trailing
            # newline; terminate it so this run's first event starts a
            # fresh line instead of gluing itself onto the corpse
            if self._f.tell() > 0:
                with open(path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        self._f.write("\n")
                        self._f.flush()

    def emit(self, event: str, **fields) -> Optional[dict]:
        """Append one event; returns the record (None when disabled).
        Per-event flush: the stream survives a SIGKILL mid-run.
        Non-finite floats are serialized as null — json.dumps' default
        NaN/Infinity literals are invalid RFC 8259 and would break strict
        consumers (jq, JSON.parse) on exactly the divergence records the
        stream exists to capture; the `anomaly` event's kind field
        carries the non-finiteness."""
        if not self.enabled or self._f is None:
            return None
        rec = {"event": event, "seq": self._seq, "t": time.time(),
               **{k: _json_finite(v) for k, v in fields.items()}}
        self._seq += 1
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        return rec

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None
        self.enabled = False

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def run_manifest(config: dict, mesh=None) -> dict:
    """The run_start payload: everything needed to interpret the rest of
    the stream (flags, jax version, topology). `config` must be
    JSON-able (argparse vars() is)."""
    import jax
    return {
        "jax_version": jax.__version__,
        "mesh_shape": dict(mesh.shape) if mesh is not None else None,
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "device_kind": jax.devices()[0].device_kind,
        "device_count": len(jax.devices()),
        "config": {k: v for k, v in sorted(config.items())
                   if isinstance(v, (str, int, float, bool, type(None)))},
    }


# --------------------------- loss-spike detector ----------------------------

@dataclasses.dataclass
class SpikeConfig:
    """EMA + z-score divergence detector knobs (--spike_* flags).
    zscore <= 0 disables the detector entirely."""
    zscore: float = 8.0    # fire when (loss - ema) / std exceeds this
    beta: float = 0.98     # EMA decay for mean AND variance
    warmup: int = 20       # observations before the detector arms


class SpikeDetector:
    """Host-side loss-spike detector over the flushed per-step losses.

    Keeps an EMA of the loss and an EMA of squared deviation; a step
    whose z-score exceeds the threshold (after warmup) is an anomaly —
    the run keeps training (policy belongs to the operator, not the
    loop) but the event stream records exactly when it went wrong
    instead of silently training through divergence. A non-finite loss
    is always anomalous, warmup or not.
    """

    def __init__(self, config: Optional[SpikeConfig] = None):
        self.config = config or SpikeConfig()
        self.mean: Optional[float] = None
        self.var: float = 0.0
        self.count: int = 0
        self._nonfinite: bool = False  # inside a non-finite run?

    def update(self, loss: float) -> Optional[dict]:
        """Feed one per-step loss; returns {kind, zscore} when anomalous,
        else None. A spiking sample is WINSORIZED into the EMA (clamped
        to mean + zscore·std) rather than excluded or taken raw: raw
        inclusion would let one spike inflate the variance and mask the
        next, full exclusion would mean a persistent level-shift (e.g. a
        LR bump settling loss on a new plateau) fires on every step
        forever — clamped updates walk the EMA toward the new level, so
        the detector re-arms after the transition."""
        c = self.config
        if c.zscore <= 0:
            return None
        if not math.isfinite(loss):
            # NaN is absorbing (every later loss stays NaN): fire on the
            # TRANSITION only, or a 100k-step diverged run would emit one
            # anomaly line per remaining step — the same stream-sizing
            # rule the throttle events follow
            if self._nonfinite:
                return None
            self._nonfinite = True
            return {"kind": "nonfinite_loss", "zscore": None}
        self._nonfinite = False
        if self.mean is None:
            self.mean, self.count = loss, 1
            return None
        dev = loss - self.mean
        std = math.sqrt(self.var)
        z = dev / std if std > 0 else 0.0
        armed = self.count >= c.warmup
        out = None
        if armed and std > 0 and z > c.zscore:
            out = {"kind": "loss_spike", "zscore": round(z, 2)}
            loss = self.mean + c.zscore * std  # winsorize
            dev = loss - self.mean
        self.mean = c.beta * self.mean + (1 - c.beta) * loss
        self.var = c.beta * self.var + (1 - c.beta) * dev * dev
        self.count += 1
        return out


# --------------------------- FLOP / MFU accounting --------------------------

def transformer_flops(n_params_active, n_params_frozen, B, S, n_layer,
                      n_head, head_dim, full_ft):
    """FLOPs per optimizer step (forward+backward), standard estimate:
    matmul fwd = 2*N*T; backward dx = 2*N*T always (the loss gradient
    flows through frozen weights to reach LoRA/embedding sites), dW only
    for trained weights; + attention 2*2*B*H*S^2*D fwd, doubled in bwd.

    Lifted out of bench.py so the benchmark MFU column and the training
    loop's step_stats.mfu use the SAME estimator by construction
    (tests/test_bench_contract.py pins `bench.transformer_flops is
    telemetry.transformer_flops`)."""
    T = B * S
    N = n_params_active + n_params_frozen
    fwd = 2 * N * T
    bwd = 2 * N * T + 2 * (n_params_active if not full_ft else N) * T
    attn = 4 * B * n_layer * n_head * S * S * head_dim
    return fwd + bwd + 3 * attn


# bf16 dense peak FLOP/s per chip, by device_kind substring (public specs).
# Matched longest-substring-first so "v5 lite" wins over "v5".
DEVICE_PEAK_FLOPS = {
    "v5 lite": 197e12, "v5litepod": 197e12, "v5e": 197e12,
    "v6 lite": 918e12, "v6e": 918e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def device_peak_flops(device_kind: Optional[str] = None) -> float:
    """Peak bf16 FLOP/s for this chip; 0.0 when unknown (e.g. CPU — MFU
    is then reported as None rather than against a made-up peak)."""
    if device_kind is None:
        import jax
        device_kind = jax.devices()[0].device_kind
    kind = device_kind.lower()
    for sub in sorted(DEVICE_PEAK_FLOPS, key=len, reverse=True):
        if sub in kind:
            return DEVICE_PEAK_FLOPS[sub]
    return 0.0


def mfu_from(flops_per_step: Optional[float], step_time_s: float,
             peak_flops: float) -> Optional[float]:
    """Model FLOP utilization for one step; None when either side of the
    ratio is unknown (no analytic estimate, or no known peak)."""
    if not flops_per_step or step_time_s <= 0 or peak_flops <= 0:
        return None
    return flops_per_step / step_time_s / peak_flops
