"""Unified run-telemetry: append-only JSONL event stream, loss-spike
detection, and the shared FLOP/MFU accounting.

The reference ships a real observability stack for its mobile loop —
leveled Logger, CSV MetricsLogger, the RSS/performance monitors
(performance_monitor.h), and the energy telemetry feeding the throttler
(power_monitor.cpp) — but none of it is machine-readable per RUN: there
is no record of why a step was slow, whether the run stayed healthy, or
what fraction of peak FLOPs it achieved. This module is the TPU
rebuild's answer: one run-scoped, crash-durable JSONL stream that every
training/eval entry point writes through, with a fixed event taxonomy
(`EVENT_SCHEMA`) that tools/telemetry_report.py and
tests/test_telemetry.py both validate against, so the contract cannot
drift from the implementation.

Design rules (DESIGN.md §13, fleet-extended by §14):
  - per-host shards: under multi-host EVERY process writes — the
    coordinator to the requested path, host k to `<path>.host<k>`
    (`shard_path`/`Telemetry.for_process`), each record host-stamped —
    so a stalled worker leaves evidence; the CSV/JSONL/checkpoint sinks
    in cli/common.run_training stay coordinator-only;
  - crash-durable: every event is written and flushed individually, so
    a killed run keeps everything up to its last completed flush; a
    resumed run APPENDS to the same stream, continuing the monotonic
    `seq` from the last valid line (a truncated tail line — the process
    died mid-write — is skipped, not fatal);
  - zero-sync invariant: nothing here touches the device. On-device
    health metrics (train/trainer.py param_norm, update_ratio,
    nonfinite_count) ride the step loop's existing buffered-metrics
    device_get; telemetry only formats what that single fetch returned.

MFU accounting lives here — `transformer_flops` was lifted OUT of
bench.py (which now imports it) so the benchmark's MFU column and the
in-loop `step_stats.mfu` agree by construction
(tests/test_bench_contract.py pins the identity).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import faulthandler
import json
import math
import os
import statistics
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

# --------------------------- event taxonomy ---------------------------------

_NUM = (int, float)
_OPT_NUM = (int, float, type(None))
_OPT_STR = (str, type(None))

# Per-event required payload fields and their allowed types. Every event
# additionally carries the envelope: event (str), seq (int, monotonic per
# stream), t (float unix time), and — since the fleet layer (DESIGN.md
# §14) — host (int process index; 0 on single-host, optional for
# back-compat with pre-fleet streams). Extra fields are ALLOWED (the
# schema is a floor, not a ceiling) so events can grow without breaking
# old readers.
EVENT_SCHEMA: Dict[str, Dict[str, tuple]] = {
    # one per run, always the stream's first event of that run
    "run_start": {
        "jax_version": (str,),
        "mesh_shape": (dict, type(None)),
        "process_count": (int,),
        "process_index": (int,),
        "device_kind": (str,),
        "device_count": (int,),
        "config": (dict,),
    },
    # one per compiled executable (wall time + XLA's own FLOP count +
    # compiled-peak HBM from the memory analysis)
    "compile": {
        "step": (int,),
        "wall_s": _NUM,
        "flops": _OPT_NUM,          # compiled.cost_analysis(); None if n/a
        "peak_hbm_mb": _OPT_NUM,
    },
    # periodic, one per metrics flush (interval-averaged timings; the
    # loss/health fields are the interval's LAST step). loss/ema are
    # null exactly when the value was non-finite (strict-JSON rule).
    "step_stats": {
        "step": (int,),
        "loss": _OPT_NUM,
        "ema": _OPT_NUM,
        "lr": _NUM,
        "grad_norm": _OPT_NUM,
        "step_time_ms": _NUM,
        "host_wait_ms": _NUM,
        "slept_ms": _OPT_NUM,       # governor sleep inside the interval
        "tok_s": _NUM,
        "mfu": _OPT_NUM,            # None when peak FLOPs unknown (CPU)
        "param_norm": _OPT_NUM,     # None on step builders without the
        "update_ratio": _OPT_NUM,   # on-device health metrics
        "nonfinite_count": _OPT_NUM,
        "skipped": _OPT_NUM,        # skip_nonfinite guard: COUNT of
                                    # skipped updates in this flush
                                    # interval (None on step builders
                                    # without the guard's metric)
        "hbm_mb": _OPT_NUM,         # null when neither live memory_stats
                                    # nor a compiled-peak estimate exists
                                    # (round 16: a backend without stats
                                    # used to masquerade as 0 MB)
        "queue_depth": _OPT_NUM,    # input-pipeline gauge (None: no stream)
        "host_step_ms": (dict, type(None)),  # {host: per-step ms} from the
                                    # last straggler-cadence gather; None
                                    # when --straggler_cadence is off
        "tenants": (dict, type(None)),  # round-18 multi-tenant engine:
                                    # {name: {slot, step, loss, tokens,
                                    # wait_ms}} per resident adapter job
                                    # (None / absent on solo training —
                                    # optional on read)
    },
    # governor throttle decision (system/governor.py event_sink)
    "throttle": {
        "step": (int,),
        "sleep_ms": _NUM,
        "battery": _OPT_NUM,
        "temp": _OPT_NUM,
        "source": (str,),           # "schedule" | "telemetry"
    },
    # host-side loss-spike / divergence detector fired (loss is null
    # exactly for kind=nonfinite_loss — strict-JSON rule), OR a
    # transient-but-survived incident: kind=data_retry (the streaming
    # data pipeline hit an I/O error and is backing off instead of
    # killing the run; extra fields carry attempt/error/backoff_s).
    # kind=divergence is the SUSTAINED form — divergence_run
    # consecutive spiking steps, a level-shift rather than a blip —
    # and is the rollback policy's trigger; one-off loss_spike events
    # deliberately are not (DESIGN.md §20).
    "anomaly": {
        "step": (int,),
        "kind": (str,),             # "loss_spike" | "divergence"
                                    # | "nonfinite_loss" | "data_retry"
        "loss": _OPT_NUM,
        "ema": _OPT_NUM,
        "zscore": _OPT_NUM,
    },
    # loss/ppl are null for evals that aren't NLL-shaped (eval_mmlu
    # reports macro_accuracy/micro_accuracy as extra fields instead)
    "eval": {
        "step": (int,),
        "loss": _OPT_NUM,
        "ppl": _OPT_NUM,
        "tokens": (int,),
    },
    # one per completed checkpoint WRITE (emitted by io/async_ckpt.py's
    # writer — possibly from its background thread). wall_s is the
    # BLOCKING cost the save charged to the step loop (snapshot only
    # under --async_save; snapshot + write on the sync oracle path) —
    # the number the goodput `checkpoint` bucket counts; write_ms/bytes/
    # mb_s describe the disk write, which under async overlaps `step`.
    "checkpoint": {
        "step": (int,),
        "final": (bool,),
        "wall_s": _NUM,
        # round-10 snapshot/write split (optional on READ: pre-async
        # streams carry only step/final/wall_s)
        "snapshot_ms": _NUM,
        "write_ms": _NUM,
        "bytes": (int,),
        "mb_s": _OPT_NUM,           # None when bytes/write_ms unknown
        "async": (bool,),
    },
    # a snapshot superseded before its write started: the async writer's
    # depth-1 queue coalesces to the newest snapshot when saves outpace
    # the disk (backpressure by dropping stale recovery points, not by
    # growing an unbounded queue of whole-tree host copies)
    "ckpt_dropped": {
        "step": (int,),             # the dropped snapshot's step
        "superseded_by": (int,),    # the snapshot that replaced it
    },
    # one host's measured per-step time pulled away from the fleet: fired
    # by the coordinator after a --straggler_cadence cross-host gather
    # when host_ms > straggler_mult * fleet median
    "straggler": {
        "step": (int,),
        "slow_host": (int,),        # NOT "host": that's the envelope's
                                    # writer stamp (the coordinator
                                    # emits this about another process)
        "host_ms": _NUM,            # the slow host's median step ms
        "fleet_ms": _NUM,           # fleet median over the same window
        "ratio": _NUM,              # host_ms / fleet_ms
    },
    # hang watchdog fired: no step completed within the armed deadline.
    # The Python stacks of every thread are in stacks_file (faulthandler
    # dump) and device_probe says whether a trivial device op still
    # completes ("ok" | "timeout" | "error:<type>" | "skipped").
    "hang": {
        "step": (int,),             # last COMPLETED step
        "stall_s": _NUM,            # time since the last completed step
        "deadline_s": _NUM,         # the armed deadline that expired
        "stacks_file": (str,),
        "device_probe": (str,),
        "action": (str,),           # "continue" | "abort"
    },
    # serving-request lifecycle (serve/engine.py): one event per phase
    # transition — enqueue (submit), admit (prefill issued; queue_ms),
    # first_token (ttft_ms closes), finish (new_tokens/ttft/tpot final),
    # and the TERMINAL failure phases the round-14 robustness layer
    # added: cancel, reject (admission refused: queue full / shed /
    # shutdown), timeout (deadline blown — queued requests never
    # prefill, active ones return partial output), error (the request
    # was in flight when a step-dispatch exception was contained). A
    # request emits EXACTLY ONE terminal phase (finish|cancel|reject|
    # timeout|error). The SLO numbers telemetry_report's TTFT/TPOT
    # percentiles, req/s, and reject/timeout/error rates are computed
    # from these.
    "request": {
        "id": (int,),
        "phase": (str,),            # REQUEST_PHASES (validated below)
        "prompt_tokens": (int,),
        "adapter": (int, type(None)),  # bank slot; None = base-only
        "queue_ms": _OPT_NUM,       # enqueue -> admission
        "new_tokens": _OPT_NUM,     # tokens generated so far
        "ttft_ms": _OPT_NUM,        # enqueue -> first token
        "tpot_ms": _OPT_NUM,        # mean per-token after the first
        "reason": _OPT_STR,         # terminal detail: a REQUEST_REASONS
                                    # policy string on reject/timeout, the
                                    # exception type name on error, else
                                    # None (optional on read: r11 streams)
        "rid": _OPT_NUM,            # round-22 fleet-wide request id the
                                    # router stamped at ingress; rides
                                    # every phase of the request so
                                    # trace_export --router can join the
                                    # router's route/queue spans to the
                                    # replica's lifecycle (None / absent
                                    # on requests submitted directly to
                                    # an engine, and on pre-r22 streams)
    },
    # cadenced serve-loop health snapshot (serve/engine.py health()):
    # queue depth, slot occupancy, page-pool headroom, rolling p95 step
    # latency, and the cumulative terminal-state counters — the
    # observable the operator's load-shed/deadline policy is tuned
    # against (telemetry_report renders queue max / occupancy mean /
    # free-page floor from these).
    "serve_stats": {
        "step": (int,),             # decode_steps at the snapshot
        "queue_depth": (int,),
        "active": (int,),           # occupied slots
        "occupancy": _NUM,          # active / num_slots
        "free_blocks": (int,),      # page-pool headroom
        "p95_step_ms": _OPT_NUM,    # rolling window; None before step 1
        "finished": (int,),         # cumulative terminal-state counters
        "cancelled": (int,),
        "rejected": (int,),
        "timeout": (int,),
        "error": (int,),
        # round-16 HBM fields (optional on read: r14 streams): live
        # device bytes (null on backends without memory_stats) and the
        # static KV-pool footprint the admission preflight charged
        "hbm_mb": _OPT_NUM,
        "pool_mb": _OPT_NUM,
        # round-20 mesh shape [dp, tp] (optional on read: pre-sharding
        # streams); [1, 1] is the single-chip engine
        "mesh": (list,),
        # round-21 shared-prefix fields (optional on read: pre-r21
        # streams): fraction of looked-up prompt tokens served from
        # cached pages (null until the first lookup, or with the cache
        # off) and cumulative copy-on-write page copies (full-hit
        # re-feeds splitting their divergence block)
        "prefix_hit_rate": _OPT_NUM,
        "cow_copies": _OPT_NUM,
        # round-22 pool-occupancy numerator (optional on read: pre-r22
        # streams): pages held by live requests — with free_blocks it
        # gives the registry's mft_serve_pool_occupancy gauge (parked
        # cache pages count as free in both fields)
        "blocks_in_use": _OPT_NUM,
    },
    # one memory-admission verdict (core/memory_guard.py, DESIGN.md
    # §21): immediately post-compile (phase=preflight), on a caught
    # RESOURCE_EXHAUSTED at dispatch (phase=dispatch), or at serve
    # build (phase=serve_build). est_mb = compiled peak + unaccounted
    # live bytes; cap_mb = --hbm_cap_mb | memory_stats bytes_limit |
    # device-kind table; verdict "unknown" when either side is
    # unavailable (admission never refuses on a guess).
    "mem_check": {
        "est_mb": _OPT_NUM,
        "cap_mb": _OPT_NUM,
        "verdict": (str,),          # "ok" | "over" | "unknown"
        "phase": (str,),
    },
    # one degradation-ladder decision (cli/common.run_training): a
    # failed preflight (or dispatch RESOURCE_EXHAUSTED) under
    # --on_oom_risk=degrade walked one rung — remat -> accum_x2 ->
    # offload — recompiling and re-preflighting after each. est_mb is
    # the estimate that FORCED the rung (the next mem_check carries
    # the post-rung estimate).
    "degrade": {
        "step": _OPT_NUM,           # None at preflight (no step ran yet)
        "rung": (str,),             # a memory_guard.LADDER name
        "from": (str,),
        "to": (str,),
        "est_mb": _OPT_NUM,
    },
    # one checkpoint-integrity verdict per candidate a load path
    # visited (io/checkpoints.resolve_checkpoint — --resume_from, the
    # in-process rollback, the serve AdapterBank hot-swap): ok=false
    # names why the candidate was rejected (checksum_mismatch:<tensor>,
    # manifest_missing/stale, malformed, size_mismatch) and the walk
    # falls back DOWN the lineage chain instead of crashing on — or
    # silently loading — the newest file (DESIGN.md §20).
    "ckpt_verify": {
        "path": (str,),
        "ok": (bool,),
        "reason": _OPT_STR,         # None exactly when ok
        "step": _OPT_NUM,           # lineage step; None when unknown
        "action": _OPT_STR,         # "load" | "reject"
    },
    # one in-process rollback decision (cli/common.run_training closing
    # the SpikeDetector loop, DESIGN.md §20): on sustained divergence /
    # a skipped-step streak / nonfinite loss the loop reloads the
    # last-known-good verified checkpoint WITHOUT restarting the
    # process or recompiling the step, fast-forwards the data stream,
    # and keeps training. ok=false records a rollback that could not
    # happen (no verified checkpoint, or budget exhausted).
    "rollback": {
        "step": (int,),             # the step the trigger fired at
        "reason": (str,),           # divergence | skip_streak |
                                    # nonfinite_loss | ...
        "ok": (bool,),
        "to_step": _OPT_NUM,        # resumed loop step (None on ok=false)
        "steps_lost": _OPT_NUM,     # step - to_step (recovery cost)
        "ckpt": _OPT_STR,           # the checkpoint file loaded
        "data_offset": _OPT_NUM,    # extra data-stream skip applied
        "budget_left": _OPT_NUM,    # --rollback_budget remaining
    },
    # preemption drain began (core/preempt.py + cli/common.run_training):
    # a SIGTERM/SIGINT was observed at a step boundary; what follows is
    # the final flush, one atomic checkpoint, and a run_end with
    # reason="preempted" — then exit EXIT_PREEMPTED (75, resumable).
    "preempt": {
        "step": (int,),             # the drain step (last completed + 1)
        "signal": (str,),           # "SIGTERM" | "SIGINT"
    },
    # one completed host span (core/trace.py Tracer, opt-in via
    # --trace_spans / ServeConfig.trace_spans): t0 is a MONOTONIC
    # time.perf_counter() stamp — the same clock as the envelope's
    # t_mono — and dur_ms the span's length, so tools/trace_export.py
    # places it on the wall timeline via the per-host (t - t_mono)
    # offset without NTP-step jitter. `track` names the Perfetto row
    # inside the host's process: "phase" (the GoodputMeter buckets),
    # "ckpt" (the async writer thread), "prefetch" (the producer
    # thread), "req:<id>" (one serve request's lifecycle).
    "span": {
        "name": (str,),
        "track": (str,),
        "t0": _NUM,
        "dur_ms": _NUM,
    },
    # one completed anomaly-triggered profiler capture (core/trace.py
    # AutoProfiler, --auto_profile): a sensor fired — slow_step |
    # loss_spike | divergence | straggler | hang — and the device
    # trace of the bad window landed at `path`, budget/cooldown
    # permitting. The event is the pointer a post-mortem follows from
    # the stream to the trace.
    "profile_capture": {
        "step": (int,),
        "trigger": (str,),
        "path": (str,),
        "steps": _OPT_NUM,          # capture length in steps (None on
                                    # the hang path's bounded hold)
        "budget_left": _OPT_NUM,
    },
    # one fleet-controller decision (tools/fleet_controller.py, written
    # to <telemetry_base>.controller): the recovery layer's own
    # timeline, rendered by fleet_report next to the goodput buckets so
    # recovery cost is a visible line, not a mystery gap in step reach.
    "controller": {
        "action": (str,),           # launch|down|restart|lost|shrink|
                                    # drain|give_up|stop
        "worker": (int, type(None)),  # subject host index; None = fleet
        "reason": _OPT_STR,         # hang | exit:<code> | preempted |
                                    # sigterm | lost worker <k> | ...
        "attempt": _OPT_NUM,        # restart attempt count for `worker`
        "backoff_s": _OPT_NUM,      # exponential backoff before relaunch
        "step": _OPT_NUM,           # worker's last observed step
        "recovery_s": _OPT_NUM,     # down-observed -> relaunched wall s
    },
    # one routing decision (tools/serve_router.py, round 22): the
    # router chose (or failed to choose) a replica for request `rid`
    # from its cadenced /metrics + /healthz scrape of every replica.
    # One event per ingress request, written to the ROUTER's own
    # stream (host 0 of the fleet base path; replica engines write the
    # .host<k> shards) — trace_export --router renders it as the
    # routing instant on the router's process row, and the serve-fleet
    # report section histograms the decisions per replica.
    "route": {
        "rid": (int,),              # fleet-wide request id (stamped here,
                                    # rides the replica's request events)
        "replica": (int, type(None)),  # chosen replica index; None when
                                    # no healthy replica could take it
        "policy": (str,),           # what decided the placement:
                                    # affinity (resident-adapter match) |
                                    # least_loaded (load score argmin) |
                                    # failover (first choice was down at
                                    # dispatch; rerouted) | reject (no
                                    # healthy candidate)
        "adapter": _OPT_STR,        # requested adapter name; None = base
        "queue_depth": _OPT_NUM,    # chosen replica's scraped depth at
                                    # decision time (None on reject)
        "occupancy": _OPT_NUM,      # chosen replica's scraped occupancy
        "scrape_age_ms": _OPT_NUM,  # staleness of the snapshot the
                                    # decision read (the scrape cadence
                                    # bounds it on a healthy fleet)
        "candidates": (int,),       # healthy replicas considered
    },
    # one multi-tenant job lifecycle transition (multitenant/engine.py,
    # DESIGN.md §23): admit (job -> slot), save (periodic step-tagged
    # checkpoint), finish (budget reached; final adapter saved at
    # `path`), cancel. `step` is the TENANT-LOCAL step counter; every
    # event also carries the optional `tenant` attribution field (see
    # validate_event) so cross-event filtering by tenant needs no
    # per-event special casing.
    "tenant": {
        "name": (str,),
        "slot": (int,),             # bank slot; -1 = not resident
        "phase": (str,),            # admit | save | finish | cancel
        "step": (int,),             # tenant-local steps completed
        "job_steps": _OPT_NUM,      # the job's step budget
        "tokens": _OPT_NUM,         # cumulative trained tokens
        "loss": _OPT_NUM,
        "path": _OPT_STR,           # saved artifact (save/finish)
    },
    # one per run on orderly exit; exit != "ok" names the exception type
    # (or "preempted" for a drained run — reason carries it too, for
    # consumers that filter on a dedicated field).
    # goodput: wall-clock bucket totals (seconds) from GoodputMeter — the
    # buckets sum to the run's wall time by construction (None on entry
    # points without a metered loop, e.g. the eval CLIs).
    "run_end": {
        "steps": (int,),
        "wall_s": _NUM,
        "exit": (str,),
        "goodput": (dict, type(None)),
        "reason": _OPT_STR,         # "preempted" on the drain path
    },
    # round-23 run registry (core/run_registry.py, DESIGN.md §28): one
    # append-only, self-contained record per run REGISTRATION — phase
    # "start" when the entrypoint opens (status "running"), phase "end"
    # when it finalizes (terminal status). Both phases re-emit the full
    # identity block (git rev, config fingerprint, platform, mesh) so a
    # registry line never needs a join to interpret; a start with no
    # matching end and a dead pid resolves to "interrupted" on the next
    # registry open. The same event is mirrored into the run's own
    # --telemetry_out stream as the observatory's join key.
    "run": {
        "run_id": (str,),
        "phase": (str,),            # "start" | "end" (closed set)
        "kind": (str,),             # "train" | "eval" | "serve" | "bench"
        "tool": (str,),             # entrypoint name (basename, no .py)
        "status": (str,),           # running | ok | interrupted | <type>
        "git_rev": _OPT_STR,        # None outside a git checkout
        "config_fingerprint": _OPT_STR,
        "platform": _OPT_STR,       # "cpu" | "tpu" | ... | None
        "mesh": (dict, type(None)),
        "pid": (int,),              # liveness probe for dead-run repair
        "artifacts": (list, type(None)),
        "wall_s": _OPT_NUM,         # None on start records
    },
    # round-23 longitudinal sentinel (tools/observatory.py): one verdict
    # per gated (platform, config, metric) series — the newest sample
    # against the rolling median + MAD band of its history. Emitted
    # through a Telemetry stream so the metrics registry folds
    # mft_trend_* gauges off the same record the verdict JSON carries.
    "trend": {
        "metric": (str,),
        "config": (str,),
        "platform": (str,),         # series are platform-split: a CPU
                                    # schema-pin row never gates a TPU
                                    # perf row
        "value": _OPT_NUM,          # newest sample
        "median": _OPT_NUM,         # rolling median of the history
        "mad": _OPT_NUM,            # median absolute deviation
        "z": _OPT_NUM,              # robust z of the newest sample
                                    # (signed: + is worse)
        "direction": _OPT_STR,      # "higher" | "lower" | None
        "regressed": (bool,),
        "run": (str,),              # newest sample's run label
        "n": (int,),                # samples in the series
    },
}


# The run-registry lifecycle's CLOSED phase set (core/run_registry.py):
# exactly one "start" and one "end" per run; the validator rejects any
# other spelling, mirroring REQUEST_PHASES.
RUN_PHASES = ("start", "end")


# Fields added AFTER a schema generation was already in the wild:
# current writers always emit them, but a reader must accept their
# ABSENCE so pre-fleet (round-8) streams still validate and render —
# when present they are type-checked as usual.
OPTIONAL_FIELDS: Dict[str, frozenset] = {
    "step_stats": frozenset({"host_step_ms", "skipped", "tenants"}),
    "serve_stats": frozenset({"hbm_mb", "pool_mb", "mesh",
                              "prefix_hit_rate", "cow_copies",
                              "blocks_in_use"}),
    "run_end": frozenset({"goodput", "reason"}),
    "checkpoint": frozenset({"snapshot_ms", "write_ms", "bytes", "mb_s",
                             "async"}),
    "request": frozenset({"reason", "rid"}),
    "ckpt_verify": frozenset({"reason", "step", "action"}),
    "rollback": frozenset({"to_step", "steps_lost", "ckpt",
                           "data_offset", "budget_left"}),
}


# The request lifecycle's CLOSED phase set (serve/engine.py): the
# validator rejects any other spelling, and the emit-site scan
# (tests/test_fleet.py) pins source literals against this tuple both
# directions — a new phase lands in schema, emitter, and report in one
# review or not at all.
REQUEST_PHASES = ("enqueue", "admit", "first_token", "finish", "cancel",
                  "reject", "timeout", "error")

# The closed set of POLICY reasons a reject/timeout carries (the error
# phase instead carries the contained exception's type name — an open
# set the scan cannot and should not pin):
#   queue_full  bounded admission refused the newest arrival
#   shed        the deadline-shed policy dropped a queued request to
#               make room for a new one
#   shutdown    drain in progress (SIGTERM): queued remainder rejected
#   deadline    the request's own deadline_ms expired
#   prompt_too_long  the prompt exceeds the engine's TRUE cap
#               (max(max_prompt, max_prompt_chunked), round 21): even
#               chunked admission cannot hold its pages + max_new
REQUEST_REASONS = frozenset({"queue_full", "shed", "shutdown", "deadline",
                             "prompt_too_long"})


def validate_event(rec: Any) -> Optional[str]:
    """None if `rec` satisfies the contract, else a human-readable reason.
    Shared by tests/test_telemetry.py and tools/telemetry_report.py so the
    validator cannot fork from the schema."""
    if not isinstance(rec, dict):
        return f"not an object: {type(rec).__name__}"
    ev = rec.get("event")
    if ev not in EVENT_SCHEMA:
        return f"unknown event type: {ev!r}"
    if not isinstance(rec.get("seq"), int) or rec["seq"] < 0:
        return f"{ev}: bad seq {rec.get('seq')!r}"
    if not isinstance(rec.get("t"), (int, float)):
        return f"{ev}: bad t {rec.get('t')!r}"
    # host is envelope, stamped by the fleet layer; optional so pre-fleet
    # streams still validate
    if "host" in rec and (not isinstance(rec["host"], int)
                          or isinstance(rec["host"], bool)
                          or rec["host"] < 0):
        return f"{ev}: bad host {rec.get('host')!r}"
    # t_mono is envelope too (round 17): a monotonic perf_counter stamp
    # next to wall `t`, so trace_export span alignment never jitters
    # across NTP steps. Optional on read — pre-round-17 streams carry
    # only `t` and must keep parsing in both report tools.
    if "t_mono" in rec and (isinstance(rec["t_mono"], bool)
                            or not isinstance(rec["t_mono"], (int, float))):
        return f"{ev}: bad t_mono {rec.get('t_mono')!r}"
    # tenant is the round-18 multi-tenant attribution field: ANY event
    # may carry it (the engine stamps its per-tenant lifecycle and save
    # events), and when present it must be a tenant name string —
    # optional on read, so every pre-multitenant stream validates
    # unchanged.
    if "tenant" in rec and not isinstance(rec["tenant"], (str, type(None))):
        return f"{ev}: bad tenant {rec.get('tenant')!r}"
    for field, types in EVENT_SCHEMA[ev].items():
        if field not in rec:
            if field in OPTIONAL_FIELDS.get(ev, ()):
                continue  # pre-fleet stream: absence is legal on read
            return f"{ev}: missing field {field!r}"
        v = rec[field]
        # bool is an int subclass; reject it where a number is expected
        if isinstance(v, bool) and bool not in types:
            return f"{ev}.{field}: bool where {types} expected"
        if not isinstance(v, types):
            return f"{ev}.{field}: {type(v).__name__} not in {types}"
    if ev == "request" and rec.get("phase") not in REQUEST_PHASES:
        return f"request: unknown phase {rec.get('phase')!r}"
    if ev == "run" and rec.get("phase") not in RUN_PHASES:
        return f"run: unknown phase {rec.get('phase')!r}"
    return None


# --------------------------- the JSONL sink ---------------------------------

def shard_path(path: str, host: int) -> str:
    """Per-host shard naming (DESIGN.md §14): the coordinator keeps the
    requested path, host k > 0 appends `.host<k>` — a single-host run
    keeps the pre-fleet path and schema (records additionally carry the
    `host` envelope stamp), and a pod run leaves one mergeable shard per
    process next to it."""
    if not path or host == 0:
        return path
    return f"{path}.host{host}"


def controller_path(path: str) -> str:
    """The fleet controller's own event stream lives NEXT TO the worker
    shards, never interleaved with them (two processes appending to one
    file would collide seq numbers and corrupt the (host, seq) merge
    key): `<base>.controller`. fleet_report discovers and renders it as
    the recovery timeline beside the per-host shards (DESIGN.md §18)."""
    return f"{path}.controller" if path else path


def _scan_existing(path: str, trailing: int = 256):
    """(last_seq, trailing step_stats records) from the file's valid JSONL
    lines; (-1, []) when the file is absent/empty. Scans the whole file: it
    is read once at open, and a telemetry stream is small (one step_stats
    per flush, not per step). The trailing step_stats feed the spike
    detector's crash/resume re-seed (SpikeDetector.seed)."""
    last = -1
    tail: collections.deque = collections.deque(maxlen=trailing)
    try:
        with open(path, "rb") as f:
            for raw in f:
                try:
                    rec = json.loads(raw)
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue  # truncated tail line from a crashed writer
                if not isinstance(rec, dict):
                    continue
                s = rec.get("seq")
                if isinstance(s, int):
                    last = max(last, s)
                if rec.get("event") == "step_stats":
                    tail.append(rec)
    except OSError:
        return -1, []
    return last, list(tail)


def _json_finite(v):
    """Replace non-finite floats (recursively) with None so every
    emitted line is strict RFC 8259 JSON."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    if isinstance(v, dict):
        return {k: _json_finite(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_json_finite(x) for x in v]
    return v


class Telemetry:
    """Append-only JSONL event stream, one record per `emit` call.

    A falsy `path` (or enabled=False) makes every method a no-op, so call
    sites never branch. Appending to an existing file continues its seq
    numbering — the crash/resume contract: one stream per run directory,
    ordered across process restarts. `resumed` is True exactly then, and
    `trailing_step_stats` holds the prior run's tail of step_stats
    records (the spike-detector re-seed source).

    `host` stamps every record's envelope with the writing process index
    (fleet merge key together with seq); emit is lock-serialized so the
    hang watchdog's daemon thread can report through the same stream as
    the step loop.

    Observers (`add_observer`) see every emitted record in-process —
    the live-metrics registry (core/metrics_http.py) rides here, so the
    `/metrics` endpoint is fed from the SAME emit path the JSONL sink
    uses: one measurement, two consumers, no second instrumentation
    layer to drift. Observers run even when the stream has no file
    (metrics without --telemetry_out), and an observer exception never
    reaches the emitter.
    """

    def __init__(self, path: str = "", enabled: bool = True,
                 host: int = 0):
        self.path = path
        self.host = int(host)
        self.enabled = bool(path) and enabled
        self._f = None
        self._seq = 0
        self._lock = threading.Lock()
        self._closed = False
        self._observers: List[Callable] = []
        self.resumed = False
        self.trailing_step_stats: List[dict] = []
        if self.enabled:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            if os.path.exists(path):
                last, self.trailing_step_stats = _scan_existing(path)
                self._seq = last + 1
                self.resumed = last >= 0
            self._f = open(path, "a", encoding="utf-8")
            # a killed writer can leave a partial line with NO trailing
            # newline; terminate it so this run's first event starts a
            # fresh line instead of gluing itself onto the corpse
            if self._f.tell() > 0:
                with open(path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        self._f.write("\n")
                        self._f.flush()

    def emit(self, event: str, **fields) -> Optional[dict]:
        """Append one event; returns the record (None when disabled).
        Per-event flush: the stream survives a SIGKILL mid-run.
        Non-finite floats are serialized as null — json.dumps' default
        NaN/Infinity literals are invalid RFC 8259 and would break strict
        consumers (jq, JSON.parse) on exactly the divergence records the
        stream exists to capture; the `anomaly` event's kind field
        carries the non-finiteness."""
        with self._lock:
            # a CLOSED stream is a hard no-op for observers too: the
            # end_run double-emission guard ("emit/close no-op once
            # closed, nested handlers compose") must hold for the
            # metrics registry or a crash path would double-count
            # run_end. A stream that never had a file (metrics without
            # --telemetry_out) still feeds observers.
            if self._closed:
                return None
            writable = self.enabled and self._f is not None
            if not writable and not self._observers:
                return None
            # envelope last: a payload field may not shadow the stream's
            # identity keys (event/seq/t/t_mono/host) — the straggler
            # event learned this the hard way (its slow-host field is
            # named slow_host for exactly this reason). t_mono is the
            # monotonic sibling of wall `t` (round 17): span alignment
            # in trace_export reads the per-host (t - t_mono) offset,
            # immune to NTP steps moving wall time mid-run.
            rec = {**{k: _json_finite(v) for k, v in fields.items()},
                   "event": event, "seq": self._seq, "t": time.time(),
                   "t_mono": time.perf_counter(), "host": self.host}
            self._seq += 1
            if writable:
                self._f.write(json.dumps(rec) + "\n")
                self._f.flush()
            for ob in self._observers:
                try:
                    ob(rec)
                except Exception:
                    pass  # a broken observer must not kill the emitter
            # contract: None exactly when nothing was durably written
            # (observers are best-effort consumers, not the stream)
            return rec if writable else None

    def add_observer(self, fn: Callable[[dict], Any]) -> None:
        """Register an in-process consumer of every emitted record
        (called under the emit lock, record-at-a-time, exceptions
        swallowed). The live-metrics registry attaches here."""
        with self._lock:
            self._observers.append(fn)

    def flush_tail(self):
        """Best-effort durability barrier before a hard exit
        (`os._exit` skips every Python-level cleanup): take the emit
        lock — so no write is mid-flight in another thread — flush the
        Python buffer through to the OS, fsync, and newline-terminate
        the file if its last byte is not '\\n'. After this returns, the
        stream's tail is a complete line: a reader (fleet_report) never
        has to skip a truncated record from an aborted process, and the
        last event emitted (the watchdog's `hang`) is durable."""
        with self._lock:
            if self._f is None or not self.enabled:
                return
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
                with open(self.path, "rb+") as rf:
                    rf.seek(0, os.SEEK_END)
                    if rf.tell() > 0:
                        rf.seek(-1, os.SEEK_END)
                        if rf.read(1) != b"\n":
                            rf.write(b"\n")
                            rf.flush()
                            os.fsync(rf.fileno())
            except OSError:
                pass  # best-effort: the abort proceeds regardless

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
            self.enabled = False
            self._closed = True

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def last_seq(self) -> int:
        """seq of the most recently emitted record (-1: none yet) — the
        hang event reports it so a post-mortem can line the stall up
        against the stream's tail."""
        return self._seq - 1

    @classmethod
    def for_process(cls, path: str) -> "Telemetry":
        """The fleet-aware stream for THIS process: coordinator writes the
        requested path, every other process its `.host<k>` shard, all
        host-stamped. Replaces the pre-fleet coordinator-only muting —
        under multi-host a stalled non-coordinator used to be invisible;
        now every host leaves a mergeable record
        (tools/fleet_report.py)."""
        if not path:
            return cls("")
        import jax
        host = jax.process_index()
        return cls(shard_path(path, host), host=host)


def run_manifest(config: dict, mesh=None) -> dict:
    """The run_start payload: everything needed to interpret the rest of
    the stream (flags, jax version, topology). `config` must be
    JSON-able (argparse vars() is)."""
    import jax
    return {
        "jax_version": jax.__version__,
        "mesh_shape": dict(mesh.shape) if mesh is not None else None,
        "process_count": jax.process_count(),
        "process_index": jax.process_index(),
        "device_kind": jax.devices()[0].device_kind,
        "device_count": len(jax.devices()),
        "config": {k: v for k, v in sorted(config.items())
                   if isinstance(v, (str, int, float, bool, type(None)))},
    }


# --------------------------- loss-spike detector ----------------------------

@dataclasses.dataclass
class SpikeConfig:
    """EMA + z-score divergence detector knobs (--spike_* flags).
    zscore <= 0 disables the detector entirely."""
    zscore: float = 8.0    # fire when (loss - ema) / std exceeds this
    beta: float = 0.98     # EMA decay for mean AND variance
    warmup: int = 20       # observations before the detector arms
    # sustained-divergence threshold: this many CONSECUTIVE spiking
    # steps escalate the anomaly kind from loss_spike (transient blip)
    # to divergence (level-shift) — the distinction the rollback policy
    # keys on, so one bad batch never triggers a rollback but a run
    # walking away from its loss curve does. <= 0 disables escalation.
    divergence_run: int = 3


class SpikeDetector:
    """Host-side loss-spike detector over the flushed per-step losses.

    Keeps an EMA of the loss and an EMA of squared deviation; a step
    whose z-score exceeds the threshold (after warmup) is an anomaly —
    the run keeps training (policy belongs to the operator, not the
    loop) but the event stream records exactly when it went wrong
    instead of silently training through divergence. A non-finite loss
    is always anomalous, warmup or not.
    """

    def __init__(self, config: Optional[SpikeConfig] = None):
        self.config = config or SpikeConfig()
        self.mean: Optional[float] = None
        self.var: float = 0.0
        self.count: int = 0
        self._nonfinite: bool = False  # inside a non-finite run?
        self.streak: int = 0  # consecutive spiking steps (divergence)

    def update(self, loss: float) -> Optional[dict]:
        """Feed one per-step loss; returns {kind, zscore} when anomalous,
        else None. A spiking sample is WINSORIZED into the EMA (clamped
        to mean + zscore·std) rather than excluded or taken raw: raw
        inclusion would let one spike inflate the variance and mask the
        next, full exclusion would mean a persistent level-shift (e.g. a
        LR bump settling loss on a new plateau) fires on every step
        forever — clamped updates walk the EMA toward the new level, so
        the detector re-arms after the transition."""
        c = self.config
        if c.zscore <= 0:
            return None
        if not math.isfinite(loss):
            # NaN is absorbing (every later loss stays NaN): fire on the
            # TRANSITION only, or a 100k-step diverged run would emit one
            # anomaly line per remaining step — the same stream-sizing
            # rule the throttle events follow
            if self._nonfinite:
                return None
            self._nonfinite = True
            return {"kind": "nonfinite_loss", "zscore": None}
        self._nonfinite = False
        if self.mean is None:
            # first OBSERVED loss: seed the mean but never clobber the
            # observation count — a rollback re-arms the detector via
            # seed([], count_hint=step) with no losses to feed, and
            # resetting to 1 here would silently re-enter warmup
            # exactly when a recurring divergence needs catching
            self.mean = loss
            self.count += 1
            return None
        dev = loss - self.mean
        std = math.sqrt(self.var)
        z = dev / std if std > 0 else 0.0
        armed = self.count >= c.warmup
        out = None
        if armed and std > 0 and z > c.zscore:
            # a streak of consecutive spiking steps is not a blip but a
            # level-shift: escalate the kind to `divergence` at
            # divergence_run — the distinct trigger the rollback policy
            # consumes (a transient loss_spike must never roll a run
            # back). The streak resets on fire so a long excursion
            # re-fires every divergence_run-th step, not every step.
            self.streak += 1
            kind = "loss_spike"
            if 0 < c.divergence_run <= self.streak:
                kind = "divergence"
                self.streak = 0
            out = {"kind": kind, "zscore": round(z, 2)}
            loss = self.mean + c.zscore * std  # winsorize
            dev = loss - self.mean
        else:
            self.streak = 0
        self.mean = c.beta * self.mean + (1 - c.beta) * loss
        self.var = c.beta * self.var + (1 - c.beta) * dev * dev
        self.count += 1
        return out

    def seed(self, losses: Sequence[float], count_hint: int = 0) -> int:
        """Re-seed from a resumed run's trailing flushed losses (the
        telemetry stream's step_stats tail) so a crash/resume does NOT
        re-enter warmup: a fresh detector needs `warmup` observations
        before arming, and a spike in the first post-resume steps — the
        exact window where resume bugs (stale optimizer state, data-order
        skew) bite — would be silently missed. The historical losses walk
        the EMA mean/variance to the pre-crash level without firing
        (seeding never emits), and `count_hint` (the resumed step number)
        bumps the observation count past warmup even when the stream's
        flush cadence kept fewer than `warmup` step_stats lines. Returns
        the number of samples consumed."""
        fed = 0
        for loss in losses:
            if not isinstance(loss, (int, float)) \
                    or not math.isfinite(loss):
                continue
            if self.mean is None:
                self.mean = float(loss)
            else:
                dev = float(loss) - self.mean
                c = self.config
                self.mean = c.beta * self.mean + (1 - c.beta) * float(loss)
                self.var = c.beta * self.var + (1 - c.beta) * dev * dev
            self.count += 1
            fed += 1
        self.count = max(self.count, int(count_hint))
        return fed


# --------------------------- goodput accounting -----------------------------

# Every second of a run's wall-clock lands in exactly one bucket:
#   init           process start -> first batch requested (model load,
#                  placement, stream construction)
#   compile        blocked in XLA compilation
#   step           dispatching/retiring optimizer steps (the productive
#                  bucket; includes the flush device_get, which is time
#                  spent WAITING for useful device work)
#   input_wait     step loop blocked pulling the next batch from the
#                  input pipeline (host-bound: tokenization/refetch)
#   eval           in-loop + final evaluation
#   checkpoint     save_hook wall time
#   governor_sleep duty-cycle throttle sleeps (deliberate idleness)
#   shutdown       post-loop teardown until run_end
GOODPUT_BUCKETS = ("init", "compile", "step", "input_wait", "eval",
                   "checkpoint", "governor_sleep", "shutdown")


class GoodputMeter:
    """Wall-clock classifier: at any instant the run is in exactly ONE
    phase, `enter(phase)` charges the elapsed time to the previous one,
    so the buckets sum to total wall-clock BY CONSTRUCTION (the
    acceptance criterion's within-1% identity is structural, not
    approximate). `summary()` is the run_end `goodput` payload.

    With a `tracer` (core/trace.py, --trace_spans) every phase SEGMENT
    additionally lands as a `span` event on the "phase" track — the
    same transition that charges the bucket emits the span, so the
    exported timeline's per-bucket span sums reconcile with run_end's
    goodput buckets by construction (trace_export prints the check)."""

    def __init__(self, tracer=None):
        self.buckets = {b: 0.0 for b in GOODPUT_BUCKETS}
        self._phase = "init"
        self._mark = time.perf_counter()
        self._tracer = tracer

    @property
    def phase(self) -> str:
        return self._phase

    def enter(self, phase: str) -> None:
        assert phase in self.buckets, phase
        now = time.perf_counter()
        self.buckets[self._phase] += now - self._mark
        if self._tracer is not None:
            self._tracer.emit_span(self._phase, "phase", self._mark,
                                   (now - self._mark) * 1000.0)
        self._mark = now
        self._phase = phase

    def summary(self) -> dict:
        """Close the current phase and render {total_s, productive_frac,
        <bucket>_s...}. productive_frac = step / total — the goodput
        number: what fraction of wall-clock advanced training.
        total_s is derived from the ROUNDED buckets (not independently
        rounded), so the emitted record itself satisfies the
        sum-to-total identity, not just the internal floats."""
        self.enter(self._phase)  # charge the open phase through `now`
        out = {f"{b}_s": round(v, 4) for b, v in self.buckets.items()}
        total = round(sum(out.values()), 6)
        out["total_s"] = total
        out["productive_frac"] = round(
            out["step_s"] / total, 4) if total > 0 else 0.0
        return out


# --------------------------- step-time window -------------------------------

class StepClock:
    """Rolling host-side per-step time window (the trainer's timing
    hook for the fleet layer; re-exported as train.trainer.StepClock).

    The step loop feeds it the FLUSH-INTERVAL synced per-step average
    (the same measurement step_stats.step_time_ms publishes; governor
    sleep excluded) — under async dispatch a per-iteration wall clock
    measures only enqueue latency, so the device_get-synced interval
    average is the honest per-step number. Consumers read the MEDIAN
    (robust: one compile- or eval-inflated sample must not shift it):
    the straggler-attribution cadence gathers `median_ms()` across
    hosts, and the hang watchdog derives its deadline from the same
    window mechanism. `reset()` starts a fresh window at a cadence
    boundary."""

    def __init__(self, window: int = 512):
        self._durs: collections.deque = collections.deque(maxlen=window)

    def record(self, seconds: float) -> None:
        self._durs.append(max(float(seconds), 0.0))

    @property
    def n(self) -> int:
        return len(self._durs)

    def median_s(self) -> float:
        return statistics.median(self._durs) if self._durs else 0.0

    def median_ms(self) -> float:
        return self.median_s() * 1000.0

    def reset(self) -> None:
        self._durs.clear()


# --------------------------- hang watchdog ----------------------------------

class HangWatchdog:
    """Daemon-thread deadline on step completion (DESIGN.md §14).

    State machine: GRACE (armed at start(), deadline `grace_s` — covers
    pre-first-step setup; compile itself should be wrapped in paused()
    by the caller, as cli/common.run_training does) -> ARMED (after the
    first pet(), deadline
    max(mult x rolling-median step time, min_deadline_s), re-armed by
    every pet; SUSPENDED across known long pauses — eval, checkpoint —
    via suspend()/resume(), because such a pause may legitimately exceed
    any step-derived deadline) -> FIRED (deadline expired with no pet:
    dump ALL Python
    thread stacks via faulthandler to `stacks_file`, probe the device
    with a trivial op under a bounded side-thread join, report through
    `on_hang`, then either re-arm with a doubled deadline — so a truly
    wedged run logs O(log) hang events, not one per deadline — or abort
    the process). stop() ends the thread on every loop exit path.

    The deadline tracks the RUN'S OWN step-time distribution (rolling
    median over `window` completed steps), not a fixed constant: a
    governor-throttled 2 s/step run and a 20 ms/step LoRA run both get a
    meaningful multiple of normal. The median is robust to the
    compile-inflated first sample and to eval/checkpoint pauses, whose
    iterations pet late but are single samples.

    Everything observable is injectable (`probe_fn`, `abort_fn`,
    `clock`) so the injected-stall tests are deterministic and never
    kill the test process.
    """

    def __init__(self, mult: float = 10.0, min_deadline_s: float = 60.0,
                 grace_s: float = 300.0,
                 on_hang: Optional[Callable[[dict], Any]] = None,
                 stacks_file: str = "", abort: bool = False,
                 probe_fn: Optional[Callable[[], Any]] = None,
                 abort_fn: Optional[Callable[[int], Any]] = None,
                 window: int = 31, probe_timeout_s: float = 5.0,
                 flush_fn: Optional[Callable[[], Any]] = None):
        self.mult = float(mult)
        self.min_deadline_s = float(min_deadline_s)
        self.grace_s = float(grace_s)
        self.on_hang = on_hang
        self.stacks_file = stacks_file or os.path.join(
            tempfile.gettempdir(), f"hang_stacks_{os.getpid()}.txt")
        self.abort = bool(abort)
        self._probe_fn = probe_fn
        self._abort_fn = abort_fn or os._exit
        self._flush_fn = flush_fn
        self._probe_timeout_s = float(probe_timeout_s)
        self._clock = StepClock(window=window)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._suspended = False
        self._last_pet = time.perf_counter()
        self._last_step = -1
        self._deadline_s = max(self.grace_s, self.min_deadline_s)
        self._backoff = 1.0
        self.fired = 0  # hang events raised (test + report observable)
        self._thread: Optional[threading.Thread] = None

    # -- step-loop side -----------------------------------------------------
    def start(self) -> "HangWatchdog":
        # the GRACE clock starts HERE, not at construction: the caller
        # may build the watchdog early in setup and arm it only at the
        # loop, and that gap must not count against the grace deadline
        self._last_pet = time.perf_counter()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hang-watchdog")
        self._thread.start()
        return self

    def pet(self, step: int, step_s: Optional[float] = None) -> None:
        """A step completed: re-arm the idle deadline; with `step_s`,
        also feed a duration sample and recompute the deadline. The two
        are split because under async dispatch the per-iteration wall
        time is just enqueue latency — the honest duration is the
        flush-interval synced average, so the loop pets every iteration
        (idle reset) and feeds samples only at flush boundaries."""
        with self._lock:
            if step_s is not None:
                self._clock.record(step_s)
                self._deadline_s = max(self.mult * self._clock.median_s(),
                                       self.min_deadline_s)
            self._backoff = 1.0
            self._last_step = step
            self._last_pet = time.perf_counter()
        self._wake.set()

    def touch(self) -> None:
        """Reset the idle clock without a completed step."""
        with self._lock:
            self._last_pet = time.perf_counter()
        self._wake.set()

    def suspend(self) -> None:
        """Stop the deadline clock across a legitimate long pause the
        loop KNOWS about (eval, checkpoint save): the pause may exceed
        any step-derived deadline, and the watchdog must not fire MID
        pause — a touch() after the pause returns would be too late."""
        with self._lock:
            self._suspended = True
        self._wake.set()

    def resume(self) -> None:
        """End a suspend(): the idle clock restarts from now."""
        with self._lock:
            self._suspended = False
            self._last_pet = time.perf_counter()
        self._wake.set()

    @contextlib.contextmanager
    def paused(self):
        """suspend()/resume() as a with-block: the resume cannot be
        forgotten even if the pause body raises."""
        self.suspend()
        try:
            yield
        finally:
            self.resume()

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- watchdog-thread side -----------------------------------------------
    def _probe_device(self) -> str:
        """Run the probe in a bounded side thread: the whole point of the
        probe is that a wedged collective may never return, and the
        watchdog thread must survive to write the report."""
        if self._probe_fn is None:
            return "skipped"
        result = {}

        def go():
            try:
                self._probe_fn()
                result["r"] = "ok"
            except BaseException as e:  # noqa: BLE001 — report, not mask
                result["r"] = f"error:{type(e).__name__}"

        t = threading.Thread(target=go, daemon=True, name="hang-probe")
        t.start()
        t.join(timeout=self._probe_timeout_s)
        return result.get("r", "timeout")

    def _dump_stacks(self) -> None:
        try:
            with open(self.stacks_file, "a") as f:
                f.write(f"=== hang at {time.strftime('%Y-%m-%d %H:%M:%S')}"
                        f" (last step {self._last_step}) ===\n")
                f.flush()
                faulthandler.dump_traceback(file=f, all_threads=True)
        except OSError:
            pass  # the report event still fires

    def _run(self) -> None:
        while not self._stop:
            with self._lock:
                deadline = self._deadline_s * self._backoff
                idle = time.perf_counter() - self._last_pet
                suspended = self._suspended
            if suspended:
                # clock stopped (known pause); resume() wakes us
                self._wake.wait(timeout=0.25)
                self._wake.clear()
                continue
            if idle < deadline:
                # sleep only to the earliest possible expiry; a pet wakes
                # us immediately so the loop re-reads the fresh deadline
                self._wake.wait(timeout=max(deadline - idle, 0.02))
                self._wake.clear()
                continue
            # deadline expired with no completed step: FIRED
            self._dump_stacks()
            probe = self._probe_device()
            self.fired += 1
            payload = {"step": self._last_step,  # last COMPLETED step
                       "stall_s": round(idle, 3),
                       "deadline_s": round(deadline, 3),
                       "stacks_file": self.stacks_file,
                       "device_probe": probe,
                       "action": "abort" if self.abort else "continue"}
            if self.on_hang is not None:
                try:
                    self.on_hang(payload)
                except Exception:
                    pass  # reporting failure must not kill the watchdog
            if self.abort:
                # a wedged collective cannot be unwound by raising in
                # another thread; hard-exit is the honest abort. But
                # os._exit skips every buffer flush, so FIRST run the
                # caller's flush barrier (Telemetry.flush_tail): it
                # serializes against any emit mid-write in the step
                # loop's thread and newline-terminates the stream — the
                # shard a post-mortem reads back ends with the complete
                # hang record, not a truncated line fleet_report must
                # skip.
                if self._flush_fn is not None:
                    try:
                        self._flush_fn()
                    except Exception:
                        pass  # the abort proceeds regardless
                self._abort_fn(113)
                return
            with self._lock:
                self._last_pet = time.perf_counter()
                self._backoff *= 2.0  # O(log) events while truly wedged


# --------------------------- partial goodput (reader side) ------------------

def partial_goodput(events: Sequence[dict]) -> dict:
    """Best-effort goodput buckets for a TRUNCATED stream (killed run, no
    run_end): reconstruct what the events themselves carry — compile and
    checkpoint wall times are explicit, governor sleep totals ride in
    step_stats.slept_ms, and input-wait is the flush-interval host-wait
    fraction applied to the observed step span. Marked partial=True; the
    buckets do NOT sum to wall-clock (that identity needs the writer-side
    GoodputMeter)."""
    compile_s = sum(e.get("wall_s") or 0.0 for e in events
                    if e.get("event") == "compile")
    ckpt_s = sum(e.get("wall_s") or 0.0 for e in events
                 if e.get("event") == "checkpoint")
    stats = [e for e in events if e.get("event") == "step_stats"]
    sleep_s = sum((e.get("slept_ms") or 0.0) for e in stats) / 1000.0
    times = sum(e.get("step_time_ms") or 0.0 for e in stats)
    waits = sum(e.get("host_wait_ms") or 0.0 for e in stats)
    wait_frac = waits / times if times > 0 else 0.0
    first_t = events[0]["t"] if events else 0.0
    last_t = events[-1]["t"] if events else 0.0
    span = max(last_t - first_t, 0.0)
    return {
        "partial": True,
        "compile_s": round(compile_s, 4),
        "checkpoint_s": round(ckpt_s, 4),
        "governor_sleep_s": round(sleep_s, 4),
        "input_wait_frac_of_step": round(wait_frac, 4),
        "observed_span_s": round(span, 4),
    }


# --------------------------- FLOP / MFU accounting --------------------------

def transformer_flops(n_params_active, n_params_frozen, B, S, n_layer,
                      n_head, head_dim, full_ft):
    """FLOPs per optimizer step (forward+backward), standard estimate:
    matmul fwd = 2*N*T; backward dx = 2*N*T always (the loss gradient
    flows through frozen weights to reach LoRA/embedding sites), dW only
    for trained weights; + attention 2*2*B*H*S^2*D fwd, doubled in bwd.

    Lifted out of bench.py so the benchmark MFU column and the training
    loop's step_stats.mfu use the SAME estimator by construction
    (tests/test_bench_contract.py pins `bench.transformer_flops is
    telemetry.transformer_flops`)."""
    T = B * S
    N = n_params_active + n_params_frozen
    fwd = 2 * N * T
    bwd = 2 * N * T + 2 * (n_params_active if not full_ft else N) * T
    attn = 4 * B * n_layer * n_head * S * S * head_dim
    return fwd + bwd + 3 * attn


# bf16 dense peak FLOP/s per chip, by device_kind substring (public specs).
# Matched longest-substring-first so "v5 lite" wins over "v5".
DEVICE_PEAK_FLOPS = {
    "v5 lite": 197e12, "v5litepod": 197e12, "v5e": 197e12,
    "v6 lite": 918e12, "v6e": 918e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def device_peak_flops(device_kind: Optional[str] = None) -> float:
    """Peak bf16 FLOP/s for this chip; 0.0 when unknown (e.g. CPU — MFU
    is then reported as None rather than against a made-up peak)."""
    if device_kind is None:
        import jax
        device_kind = jax.devices()[0].device_kind
    kind = device_kind.lower()
    for sub in sorted(DEVICE_PEAK_FLOPS, key=len, reverse=True):
        if sub in kind:
            return DEVICE_PEAK_FLOPS[sub]
    return 0.0


def mfu_from(flops_per_step: Optional[float], step_time_s: float,
             peak_flops: float) -> Optional[float]:
    """Model FLOP utilization for one step; None when either side of the
    ratio is unknown (no analytic estimate, or no known peak)."""
    if not flops_per_step or step_time_s <= 0 or peak_flops <= 0:
        return None
    return flops_per_step / step_time_s / peak_flops
