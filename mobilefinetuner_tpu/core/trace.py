"""Host-span tracing + anomaly-triggered profiler capture (DESIGN.md §22).

The telemetry stream (core/telemetry.py) records WHAT happened; this
module records WHEN, precisely enough to draw: `span` events carry a
monotonic begin stamp and a duration on a named TRACK, so a whole run —
the GoodputMeter's exclusive phases, the serve loop's per-request
queue/prefill/decode lifecycle, the async-checkpoint writer thread, the
prefetch producer — renders as one timeline in ui.perfetto.dev after
`tools/trace_export.py` converts the stream. Spans ride the SAME
crash-durable JSONL stream as every other event (one `span` record per
completed span, emitted at span END), so a killed run keeps every span
that finished before the kill and the exporter needs no second file.

Clock discipline: span `t0` uses time.perf_counter() — the same
monotonic clock the telemetry envelope's `t_mono` stamp uses — so the
exporter can place spans on the wall-clock timeline via the per-host
(t - t_mono) offset without NTP-step jitter corrupting durations.

Span emission is OPT-IN (--trace_spans / ServeConfig.trace_spans): a
traced step loop emits a handful of spans per step, which is exactly
what you want while looking at a problem and more than you want in a
month-long stream. Everything here is host-side: no device access, no
jax import on the Tracer path (the zero-sync invariant extends to the
trace layer — tests pin it structurally).

The second half is the flight recorder (`AutoProfiler`, --auto_profile):
a manually pre-scheduled --profile_dir window is useless for the
anomalies the sensors actually catch, so this arms a ONE-SHOT
jax.profiler capture when a sensor fires — slow-step multiple over the
rolling median, loss-spike/divergence anomaly, straggler attribution,
hang watchdog pre-exit — saving the device trace of the BAD step next
to the stack dumps, under a capture budget and a cooldown so a
persistently sick run produces a few traces, not a disk full of them.
Every capture decision is a `profile_capture` telemetry event.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Callable, Optional


class Tracer:
    """Span emitter over a telemetry sink (`Telemetry.emit` signature).

    One emit site for the whole repo: every producer — the goodput
    meter, the serve engine, the checkpoint writer, the prefetch
    producer — routes through `emit_span`, so the `span` event shape
    cannot fork between threads or subsystems. Thread-safety is the
    sink's problem (Telemetry.emit is lock-serialized), which is what
    lets the checkpoint writer and prefetch producer threads trace
    through the same stream as the step loop.
    """

    def __init__(self, sink: Optional[Callable] = None,
                 enabled: bool = True):
        self._sink = sink
        self.enabled = bool(sink) and enabled

    def emit_span(self, name: str, track: str, t0: float, dur_ms: float,
                  **extra) -> None:
        """Record one completed span: `t0` is a time.perf_counter()
        stamp (the envelope's t_mono clock), dur_ms its length. Extra
        fields ride along (the schema is a floor)."""
        if not self.enabled:
            return
        self._sink(event="span", name=name, track=track,
                   t0=round(t0, 6), dur_ms=round(dur_ms, 3), **extra)

    @contextlib.contextmanager
    def span(self, name: str, track: str = "main", **extra):
        """Lexical span: emits on exit, exception or not (the span that
        raised is exactly the one a post-mortem wants on the timeline)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit_span(name, track, t0,
                           (time.perf_counter() - t0) * 1000.0, **extra)


class AutoProfiler:
    """One-shot anomaly-triggered jax.profiler capture (--auto_profile).

    State machine: IDLE -> (sensor trigger, budget left, cooldown
    elapsed) -> CAPTURING (jax.profiler trace started into its own
    subdirectory; the step loop calls `tick` after each step and the
    capture stops after `steps` of them, syncing the device first so
    the trace actually contains the dispatched work) -> COOLDOWN.
    A `profile_capture` event records every completed capture — step,
    trigger kind, path, budget left — so the stream says where the
    trace of the bad step lives.

    The hang path is different: when the watchdog fires, the step loop
    is by definition not ticking, so `capture_now` takes a bounded
    capture on the CALLER's thread (start, hold, stop) — whatever the
    device is doing while wedged lands in the trace, before a
    --watchdog 2 abort can os._exit.

    `profiler_start`/`profiler_stop` are injectable so tests never
    depend on jax.profiler internals; the default binds lazily (no jax
    import at module load). Failures inside the profiler NEVER
    propagate — a broken capture must not kill the training run it was
    meant to diagnose.

    Thread-safety: `capture_now` runs on the WATCHDOG thread while
    `trigger`/`tick` run on the step loop, so every state transition is
    lock-serialized — without it, a loop that unwedges during a hang
    capture's hold could tick the hold capture to a premature stop and
    double-finish it (and two threads could double-start the one
    profiler). The lock is held across `capture_now`'s bounded hold:
    blocking a just-unwedged loop for the hold is noise next to the
    stall that fired the watchdog, and it is what keeps the profiler
    single-owner.
    """

    def __init__(self, out_dir: str, sink: Optional[Callable] = None,
                 steps: int = 2, cooldown_s: float = 300.0,
                 budget: int = 2,
                 profiler_start: Optional[Callable[[str], None]] = None,
                 profiler_stop: Optional[Callable[[], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.out_dir = out_dir
        self._sink = sink
        self.steps = max(int(steps), 1)
        self.cooldown_s = float(cooldown_s)
        self.budget = max(int(budget), 0)
        self._clock = clock
        self._lock = threading.Lock()
        self._start = profiler_start or self._jax_start
        self._stop = profiler_stop or self._jax_stop
        self._last_capture_t: Optional[float] = None
        self._active_path: Optional[str] = None
        self._steps_left = 0
        self._trigger: Optional[str] = None
        self._n = 0
        self.captured = 0  # completed captures (test observable)

    @staticmethod
    def _jax_start(path: str) -> None:
        import jax
        jax.profiler.start_trace(path)

    @staticmethod
    def _jax_stop() -> None:
        import jax
        jax.profiler.stop_trace()

    @property
    def active(self) -> bool:
        return self._active_path is not None

    def _ready(self) -> bool:
        if self.active or self.budget <= 0:
            return False
        if self._last_capture_t is not None and \
                self._clock() - self._last_capture_t < self.cooldown_s:
            return False
        return True

    def _capture_path(self, trigger: str, step: int) -> str:
        path = os.path.join(self.out_dir,
                            f"cap{self._n}_{trigger}_step{step}")
        self._n += 1
        return path

    def trigger(self, kind: str, step: int) -> bool:
        """A sensor fired: start a capture unless one is active, the
        budget is spent, or the cooldown has not elapsed. Returns True
        exactly when a capture STARTED."""
        with self._lock:
            if not self._ready():
                return False
            path = self._capture_path(kind, step)
            try:
                os.makedirs(path, exist_ok=True)
                self._start(path)
            except Exception:
                return False  # a broken profiler must not kill the run
            self._active_path = path
            self._trigger = kind
            self._steps_left = self.steps
            return True

    def tick(self, step: int, sync: Optional[Callable] = None) -> bool:
        """One step completed under an active capture; stops the trace
        after `steps` ticks (running `sync` first so the async-
        dispatched device work is actually IN the window). Returns True
        when the capture completed on this tick."""
        with self._lock:
            if not self.active:
                return False
            self._steps_left -= 1
            if self._steps_left > 0:
                return False
            if sync is not None:
                try:
                    sync()
                except Exception:
                    pass
            return self._finish(step, steps=self.steps)

    def capture_now(self, kind: str, step: int,
                    hold_s: float = 1.0) -> bool:
        """Bounded immediate capture for callers with no step loop to
        tick — the hang watchdog's pre-exit hook: start, hold while the
        wedged device does whatever it is doing, stop, record. Never
        raises. Holds the lock for the whole start-hold-stop so the
        step loop can never tick this capture to a premature stop."""
        with self._lock:
            if not self._ready():
                return False
            path = self._capture_path(kind, step)
            try:
                os.makedirs(path, exist_ok=True)
                self._start(path)
            except Exception:
                return False
            self._active_path, self._trigger = path, kind
            time.sleep(max(hold_s, 0.0))
            # steps=None: a bounded hold, not a counted step window
            # (the schema documents exactly this)
            return self._finish(step, steps=None)

    def _finish(self, step: int, steps) -> bool:
        # caller holds self._lock; `steps` is what the capture ACTUALLY
        # covered (None for the hang path's bounded hold), not the
        # configured window — a close() mid-capture reports the steps
        # that ran, so post-mortem tooling never overstates the trace
        path, trigger = self._active_path, self._trigger
        try:
            self._stop()
        except Exception:
            self._active_path = None
            return False
        self._active_path = None
        self._last_capture_t = self._clock()
        self.budget -= 1
        self.captured += 1
        if self._sink is not None:
            self._sink(event="profile_capture", step=step,
                       trigger=trigger, path=path, steps=steps,
                       budget_left=self.budget)
        return True

    def close(self) -> None:
        """Stop a capture left open by an exiting loop (the trace of
        the steps that DID run is still worth keeping — and reported
        with the tick count that actually elapsed)."""
        with self._lock:
            if self.active:
                self._finish(-1, steps=self.steps - self._steps_left)
