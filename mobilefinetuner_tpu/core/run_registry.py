"""Append-only, crash-safe run registry (DESIGN.md §28).

Every train/eval/serve/bench entrypoint registers here: one `run` event
(core/telemetry.py EVENT_SCHEMA) at start (status "running"), one at
finalize (terminal status), both written through the SAME Telemetry
machinery the run streams use — per-record flush, truncated-tail repair
on append, strict-JSON lines — so a SIGKILL between the two leaves a
durable start record instead of nothing. Each record is self-contained
(run id, git rev, config fingerprint, platform, mesh, artifact paths):
a registry line never needs a join to interpret, which is what lets
tools/observatory.py and the report tools resolve runs by id/rev
instead of raw file paths.

Crash repair: a "start" with no matching "end" whose pid is no longer
alive is settled on the next registry open — an `interrupted` end
record is APPENDED (the registry stays append-only; nothing is ever
rewritten), so every run converges to exactly one finalized record:
normal exit, SIGKILL mid-run, or admission-reject alike.

Zero-sync: this module never imports jax. The platform/mesh facts are
passed in by the caller (which already holds them), and the git rev is
read from .git/HEAD directly — no subprocess, no device touch.

Concurrency: records are identified by run_id, not seq — two processes
appending concurrently may interleave seq numbers (each write reopens
the stream and continues the numbering it observed), which the readers
here deliberately ignore.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional

from mobilefinetuner_tpu.core.telemetry import Telemetry, validate_event

#: environment fallback for the --run_registry flag: one exported path
#: makes every entrypoint in a shell session register without per-CLI
#: plumbing (the flag, when passed, wins).
REGISTRY_ENV = "MFT_RUN_REGISTRY"

#: terminal statuses the settle pass never rewrites; anything else on a
#: start record ("running") is a candidate for interrupted-repair.
TERMINAL = ("ok", "interrupted", "preempted")


def config_fingerprint(config: Optional[dict]) -> Optional[str]:
    """12-hex sha256 over the JSON-scalar subset of `config`, sorted —
    the same filter run_manifest applies, so the fingerprint is stable
    across flag ordering and ignores unserializable handles."""
    if not config:
        return None
    scalars = {k: v for k, v in sorted(config.items())
               if isinstance(v, (str, int, float, bool, type(None)))}
    blob = json.dumps(scalars, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def git_rev(root: str = ".") -> Optional[str]:
    """The checkout's HEAD commit (12 hex chars) read straight from
    .git — no subprocess (a registry write must stay cheap and work in
    sandboxes without a git binary). None outside a git checkout."""
    try:
        git_dir = os.path.join(root, ".git")
        if os.path.isfile(git_dir):  # worktree: "gitdir: <path>"
            with open(git_dir) as f:
                git_dir = f.read().split(":", 1)[1].strip()
        with open(os.path.join(git_dir, "HEAD")) as f:
            head = f.read().strip()
        if not head.startswith("ref:"):
            return head[:12] or None
        ref = head.split(None, 1)[1]
        ref_path = os.path.join(git_dir, ref)
        if os.path.exists(ref_path):
            with open(ref_path) as f:
                return f.read().strip()[:12] or None
        packed = os.path.join(git_dir, "packed-refs")
        if os.path.exists(packed):
            with open(packed) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 2 and parts[1] == ref:
                        return parts[0][:12]
    except (OSError, IndexError, ValueError):
        pass
    return None


def _pid_alive(pid: int) -> bool:
    """Liveness probe behind the interrupted-repair: signal 0 touches
    nothing but reports existence. PermissionError means alive (someone
    else's process)."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


class RunHandle:
    """One registered run: `finalize` appends the end record (idempotent
    — end_run-style nested handlers may race a crash path) and mirrors
    it into the run's own telemetry stream when one is attached."""

    def __init__(self, registry: "RunRegistry", payload: dict,
                 telemetry=None):
        self.registry = registry
        self.run_id = payload["run_id"]
        self._payload = payload
        self._telemetry = telemetry
        self._t0 = time.time()
        self._finalized = False

    def finalize(self, status: str = "ok",
                 artifacts: Optional[Iterable[str]] = None) -> None:
        if self._finalized:
            return
        self._finalized = True
        rec = dict(self._payload)
        rec["phase"] = "end"
        rec["status"] = str(status)
        rec["wall_s"] = round(time.time() - self._t0, 3)
        if artifacts is not None:
            merged = list(rec.get("artifacts") or [])
            merged += [a for a in artifacts if a and a not in merged]
            rec["artifacts"] = merged
        self.registry._append(rec)
        if self._telemetry is not None:
            self._telemetry.emit("run", **rec)

    def __enter__(self) -> "RunHandle":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # exception type name as the terminal status, matching the
        # run_end `exit` convention (cli/common.py end_run)
        self.finalize("ok" if exc_type is None else exc_type.__name__)


class RunRegistry:
    """The registry file: a Telemetry-written JSONL stream of `run`
    events. Construct with a path; a falsy path disables every method
    (the no-op convention Telemetry itself uses)."""

    def __init__(self, path: str):
        self.path = path or ""

    @classmethod
    def from_args(cls, args) -> Optional["RunRegistry"]:
        """--run_registry flag first, then the MFT_RUN_REGISTRY env
        var; None when neither is set (registration stays opt-in — no
        behavior change for existing callers)."""
        path = getattr(args, "run_registry", "") or \
            os.environ.get(REGISTRY_ENV, "")
        return cls(path) if path else None

    # -- write path ----------------------------------------------------------

    def _append(self, payload: dict) -> None:
        """One record through the existing telemetry flush path: open
        (append mode repairs a truncated tail and continues seq), emit
        (per-event flush), close. Short-lived handles keep concurrent
        writers from holding the file across a whole run."""
        with Telemetry(self.path) as tel:
            tel.emit("run", **payload)

    def begin(self, kind: str, tool: str, config: Optional[dict] = None,
              mesh: Optional[dict] = None, platform: Optional[str] = None,
              artifacts: Iterable[str] = (), telemetry=None,
              root: str = ".") -> RunHandle:
        """Register a run: append the start record (status "running"),
        mirror it into `telemetry` (the run's own stream) as the
        observatory's join key, and settle any dead predecessors while
        the file is open anyway. Returns the handle finalize rides."""
        run_id = (time.strftime("%Y%m%dT%H%M%S")
                  + f"-{os.getpid()}-{os.urandom(3).hex()}")
        payload = {
            "run_id": run_id,
            "phase": "start",
            "kind": str(kind),
            "tool": str(tool),
            "status": "running",
            "git_rev": git_rev(root),
            "config_fingerprint": config_fingerprint(config),
            "platform": platform,
            "mesh": dict(mesh) if mesh else None,
            "pid": os.getpid(),
            "artifacts": [a for a in artifacts if a] or None,
            "wall_s": None,
        }
        self.settle()
        self._append(payload)
        if telemetry is not None:
            telemetry.emit("run", **payload)
        return RunHandle(self, payload, telemetry=telemetry)

    # -- read path -----------------------------------------------------------

    def _raw_records(self) -> List[dict]:
        out: List[dict] = []
        try:
            with open(self.path, "rb") as f:
                lines = f.read().splitlines()
        except OSError:
            return out
        for raw in lines:
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue  # truncated tail from a killed writer
            if isinstance(rec, dict) and rec.get("event") == "run" \
                    and validate_event(rec) is None:
                out.append(rec)
        return out

    def records(self, settle: bool = True) -> List[dict]:
        """One RESOLVED record per run_id, in first-seen order: the
        start record's identity block, overlaid with its end record's
        terminal status/wall_s/artifacts when one landed. With
        settle=True (default), dead "running" records are repaired to
        "interrupted" first — so a reader never sees a zombie."""
        if settle:
            self.settle()
        runs: Dict[str, dict] = {}
        for rec in self._raw_records():
            rid = rec["run_id"]
            if rec["phase"] == "start":
                runs.setdefault(rid, dict(rec))
            else:
                base = runs.setdefault(rid, dict(rec))
                for k in ("status", "wall_s", "artifacts"):
                    if rec.get(k) is not None:
                        base[k] = rec[k]
                base["phase"] = "end"
        return list(runs.values())

    def settle(self) -> int:
        """Append `interrupted` end records for every start whose run
        never finalized and whose pid is dead — the r15 kill-safe
        contract, at registry granularity: a SIGKILLed run is marked,
        not lost, not forever "running". Returns the repair count.
        This process's own live registrations are left alone."""
        if not self.path or not os.path.exists(self.path):
            return 0
        runs: Dict[str, dict] = {}
        ended = set()
        for rec in self._raw_records():
            if rec["phase"] == "start":
                runs.setdefault(rec["run_id"], rec)
            else:
                ended.add(rec["run_id"])
        repaired = 0
        for rid, rec in runs.items():
            if rid in ended or _pid_alive(rec.get("pid", -1)):
                continue
            # drop the stream envelope (event/seq/t) — _append stamps a
            # fresh one; only the run payload is carried forward
            end = {k: v for k, v in rec.items()
                   if k not in ("event", "seq", "t")}
            end["phase"] = "end"
            end["status"] = "interrupted"
            self._append(end)
            repaired += 1
        return repaired

    def resolve(self, token: str) -> Optional[dict]:
        """A record by run_id, unique run_id prefix, or git rev (the
        LATEST run at that rev — "compare me against what main built"
        wants the newest artifact). None when nothing matches."""
        if not token:
            return None
        recs = self.records()
        for r in recs:
            if r["run_id"] == token:
                return r
        prefix = [r for r in recs if r["run_id"].startswith(token)]
        if len(prefix) == 1:
            return prefix[0]
        by_rev = [r for r in recs
                  if r.get("git_rev") and r["git_rev"].startswith(token)]
        return by_rev[-1] if by_rev else None

    def artifact_for(self, token: str,
                     suffix: str = ".json") -> Optional[str]:
        """The resolved run's first on-disk artifact with `suffix` —
        what bench_compare feeds to load_rows, byte-identical to the
        path invocation because it IS a path invocation after this."""
        rec = self.resolve(token)
        for p in (rec or {}).get("artifacts") or []:
            if p.endswith(suffix) and os.path.exists(p):
                return p
        return None


def registry_from(path_or_args: Any) -> Optional[RunRegistry]:
    """Convenience for tools: accept a raw path string or an argparse
    namespace with a run_registry attribute (env fallback either way)."""
    if isinstance(path_or_args, str):
        path = path_or_args or os.environ.get(REGISTRY_ENV, "")
        return RunRegistry(path) if path else None
    return RunRegistry.from_args(path_or_args)
