"""Version-skew shims, each the ONE copy of a jax rename.

The tree is written against current jax (where `jax.shard_map` is public
and its replication-check flag is `check_vma`); older runtimes still in
some CI containers carry shard_map under `jax.experimental.shard_map`
with the flag spelled `check_rep`. Call sites import from here so the
skew is absorbed in one place instead of at every shard_map.

(The analogous pallas rename — CompilerParams vs TPUCompilerParams — is
absorbed by ops/pallas_util.tpu_call_params for the same reason.)
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """jax.shard_map with the check_vma flag, on every supported jax."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy_sm
    return legacy_sm(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check_vma)
