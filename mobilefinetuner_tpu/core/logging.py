"""Leveled logger + CSV metrics + JSONL eval output.

TPU-native analog of the reference's logging stack
(reference: operators/finetune_ops/utils/logger.h:21-226 — leveled Logger with
file+console sinks, MetricsLogger CSV with columns
timestamp,epoch,step,loss,avg_loss,lr,step_time_ms, and OPS_LOG_* macros) and
of the CLIs' JSONL eval-append output (gpt2_lora_finetune/main.cpp:654-664).
"""

from __future__ import annotations

import csv
import json
import logging
import os
import sys
import time
from typing import Optional


def get_logger(name: str = "mft", level: str = "INFO",
               log_file: Optional[str] = None) -> logging.Logger:
    logger = logging.getLogger(name)
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    fmt = logging.Formatter(
        "[%(asctime)s] [%(levelname)s] %(message)s", "%Y-%m-%d %H:%M:%S")
    if not any(isinstance(h, logging.StreamHandler)
               and not isinstance(h, logging.FileHandler)
               for h in logger.handlers):
        sh = logging.StreamHandler(sys.stderr)
        sh.setFormatter(fmt)
        logger.addHandler(sh)
    if log_file:
        target = os.path.abspath(log_file)
        have = any(isinstance(h, logging.FileHandler)
                   and getattr(h, "baseFilename", None) == target
                   for h in logger.handlers)
        if not have:
            os.makedirs(os.path.dirname(target), exist_ok=True)
            fh = logging.FileHandler(target)
            fh.setFormatter(fmt)
            logger.addHandler(fh)
    logger.propagate = False
    return logger


class MetricsLogger:
    """CSV training-metrics sink, one row per logged step.

    Columns mirror the reference MetricsLogger (logger.h:131-190) plus
    the TPU-native observability columns: grad_norm (pre-clip global
    norm — printed in the log line since round 0 but only now persisted);
    hbm_mb — the analog of the reference's per-interval memory prints
    (main.cpp:639-642): live device bytes-in-use when the platform
    exposes memory_stats(), else the compiled peak estimate the caller
    provides; host_wait_ms, the interval-averaged time the step loop
    blocked pulling the next batch from the input pipeline (the host
    share of the host/device step-time breakdown; ~0 when the async
    prefetcher keeps up); tok_s, interval tokens/sec; and mfu, the
    model-FLOP utilization from the shared estimator
    (core/telemetry.transformer_flops — blank when the chip's peak is
    unknown, e.g. CPU). A resumed pre-change CSV is rotated to .old by
    the header-mismatch check below; tools/plot_loss.py reads both
    schemas.
    """

    COLUMNS = ["timestamp", "epoch", "step", "loss", "avg_loss", "lr",
               "grad_norm", "step_time_ms", "host_wait_ms", "tok_s",
               "mfu", "hbm_mb"]

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        if os.path.exists(path):
            with open(path, newline="") as f:
                header = f.readline().strip().split(",")
            if header != self.COLUMNS:
                # column set changed since the file was started (e.g. a
                # resumed pre-hbm_mb run): rotate rather than appending
                # rows that disagree with the header
                os.replace(path, path + ".old")
        new = not os.path.exists(path)
        self._f = open(path, "a", newline="")
        self._w = csv.writer(self._f)
        if new:
            self._w.writerow(self.COLUMNS)
            self._f.flush()

    def log(self, epoch: int, step: int, loss: float, avg_loss: float,
            lr: float, step_time_ms: float, host_wait_ms: float = 0.0,
            hbm_mb: float = 0.0, grad_norm: float = 0.0,
            tok_s: float = 0.0, mfu=None):
        self._w.writerow([f"{time.time():.3f}", epoch, step, f"{loss:.6f}",
                          f"{avg_loss:.6f}", f"{lr:.8f}",
                          f"{grad_norm:.4f}", f"{step_time_ms:.2f}",
                          f"{host_wait_ms:.2f}", f"{tok_s:.1f}",
                          "" if mfu is None else f"{mfu:.4f}",
                          f"{hbm_mb:.1f}"])
        self._f.flush()

    def close(self):
        self._f.close()


class JSONLWriter:
    """Append-only JSONL sink for eval records (main.cpp:654-664 analog)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def write(self, record: dict):
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")
