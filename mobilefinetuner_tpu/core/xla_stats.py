"""Device-memory accounting shared by the training loop and benchmarks.

The compile-time peak is the TPU-native analog of the reference's RSS
reporting (reference: scripts/Finetune/measure_rss.sh:22-42,
performance_monitor.h:18-33 MemorySnapshot): XLA's memory analysis of a
compiled program is exact for static shapes, and unlike runtime
memory_stats() it is available on every platform including the tunneled
TPU used in CI.
"""

from __future__ import annotations

import jax


def compiled_peak_bytes(compiled) -> int:
    """Peak device memory of a compiled program: arguments + temps +
    outputs minus donated aliases. Returns 0 when the backend does not
    report memory analysis."""
    try:
        ma = compiled.memory_analysis()
        return int(ma.argument_size_in_bytes + ma.temp_size_in_bytes
                   + ma.output_size_in_bytes - ma.alias_size_in_bytes)
    except Exception:
        return 0


def compiled_peak_mb(compiled) -> float:
    return compiled_peak_bytes(compiled) / 2 ** 20


def shaped_all_gathers(compiled, shape, dtypes=("f32", "bf16")) -> list:
    """HLO lines of `compiled` where an all-gather involves a tensor of
    exactly `shape` — the sharding-assertion primitive behind "the
    V-sharded embed table is never all-gathered" (vocab-parallel CE,
    ops/loss.py; asserted by tests/test_multichip.py and
    __graft_entry__.dryrun_multichip)."""
    table = "[" + ",".join(str(d) for d in shape) + "]"
    needles = [f"{dt}{table}" for dt in dtypes]
    return [ln for ln in compiled.as_text().splitlines()
            if "all-gather" in ln and any(n in ln for n in needles)]


def memory_stat(device, key: str, default=None):
    """One guarded read of `device.memory_stats()[key]`. Platforms
    return None, {}, or PARTIAL dicts — e.g. bytes_in_use present but
    bytes_limit absent — and a consumer indexing the dict directly
    KeyErrors exactly on those backends. A missing, non-dict, or
    non-numeric entry is `default`, never an exception, so every
    memory_stats consumer (live_hbm_mb here, memory_guard's capacity
    probe, the observatory's backfill) shares one contract."""
    try:
        stats = device.memory_stats() or {}
    except Exception:
        return default
    if not hasattr(stats, "get"):
        return default
    v = stats.get(key, default)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return default
    return v


_no_stats_logged = set()  # backends already warned about (log once)


def live_hbm_mb(devices=None):
    """MAX device bytes-in-use across the local devices, when the
    platform exposes memory_stats() (the tunneled TPU platform does not;
    direct TPU does; this jax's CPU backend returns an empty dict). The
    max — not device 0 — because shards can be imbalanced (e.g. a
    vocab-parallel embed remainder landing on one chip) and the binding
    constraint is the fullest device.

    Returns None — not 0.0 — when NO device reported a bytes_in_use:
    a zero would silently masquerade as "nothing allocated" in the
    telemetry hbm_mb field, when the truth is "this backend cannot
    say" (the field is emitted as null and a one-time log names the
    backend). `devices`: override for tests; defaults to
    jax.local_devices()."""
    if devices is None:
        try:
            devices = jax.local_devices()
        except Exception:
            return None
    peak = None
    platform = "unknown"
    for d in devices:
        platform = getattr(d, "platform", platform)
        in_use = memory_stat(d, "bytes_in_use")
        if in_use is not None:
            peak = max(peak or 0.0, in_use / 2 ** 20)
    if peak is None and platform not in _no_stats_logged:
        _no_stats_logged.add(platform)
        from mobilefinetuner_tpu.core.logging import get_logger
        get_logger().info(
            f"backend {platform!r} exposes no memory_stats bytes_in_use; "
            f"live-HBM telemetry will be null (compiled-peak estimates "
            f"still apply)")
    return peak


def compiled_flops(compiled) -> float:
    """XLA's own FLOP count for a compiled executable, from
    cost_analysis() — 0.0 when the backend does not report it. Absorbs
    the API's version skew (list-of-dicts per device on older jax, a
    flat dict on newer)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca.get("flops", 0.0)) if hasattr(ca, "get") else 0.0
    except Exception:
        return 0.0
