"""HBM admission: capacity resolution, preflight verdicts, and the
degradation-ladder vocabulary (DESIGN.md §21).

An out-of-memory config is the one fault class rounds 13-15 left
unrecoverable: a bad `--batch_size` kills a run minutes into setup, and
an XLA RESOURCE_EXHAUSTED mid-fleet burns controller restart budget on
a fault no restart can fix. This module turns the memory question into
an ADMISSION decision made immediately after AOT compile — when XLA's
memory analysis gives the exact per-device peak for free and nothing
expensive (data loading, stream threads, first dispatch) has happened
yet:

  est_mb   compiled peak (arguments + temps + outputs - donated
           aliases) plus any LIVE device bytes the step's own arguments
           do not account for (prefetched batches, ballast, a second
           compiled program's buffers);
  cap_mb   per-device capacity — `--hbm_cap_mb` override first (CPU
           tests drive the verdict deterministically with it), then
           the backend's memory_stats()["bytes_limit"], then a
           device-kind table of public HBM sizes (the tunneled-TPU
           platform exposes no memory_stats);
  verdict  "over" when est_mb exceeds cap_mb under the `--hbm_headroom`
           margin, "ok" when it fits, "unknown" when either side of
           the comparison is unavailable (never guess a refusal).

Consumers: cli/common.run_training (preflight + the remat -> accum x2
-> offload degradation ladder), the eval CLIs (preflight only), and
serve/engine.ServeEngine (analytic pool+params admission at build).
Every check lands in the telemetry stream as a `mem_check` event and
every ladder decision as a `degrade` event (core/telemetry.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.core.xla_stats import compiled_peak_mb, live_hbm_mb

log = get_logger()


class MemoryAdmissionError(RuntimeError):
    """A config that cannot fit device memory was refused — at preflight
    (fail-fast, nothing ran) or after the degradation ladder ran dry
    (`ladder` records every rung attempted). Named so fleet tooling can
    tell an inadmissible CONFIG from a crash a restart might fix: the
    r13 controller must not burn restart budget re-launching it."""

    def __init__(self, message: str, check: "MemCheck" = None,
                 ladder: Tuple[str, ...] = ()):
        super().__init__(message)
        self.check = check
        self.ladder = tuple(ladder)


# Per-device HBM capacity in MB by device_kind substring (public chip
# specs) — the fallback when the platform exposes no
# memory_stats()["bytes_limit"] (the tunneled TPU used in CI does not).
# Matched longest-substring-first so "v5 lite" wins over "v5", same
# convention as telemetry.DEVICE_PEAK_FLOPS.
DEVICE_HBM_MB = {
    "v5 lite": 16 * 1024, "v5litepod": 16 * 1024, "v5e": 16 * 1024,
    "v6 lite": 32 * 1024, "v6e": 32 * 1024,
    "v5p": 95 * 1024,
    "v4": 32 * 1024,
    "v3": 16 * 1024,
    "v2": 8 * 1024,
}

# The ordered, bounded degradation ladder (DESIGN.md §21): cheapest
# semantic change first. Each rung recompiles and re-preflights; loss
# trajectory stays parity-pinned (remat recomputes identical math;
# accum x2 halves the scanned micro-batch at CONSTANT global batch —
# only float reassociation moves, <=1e-5; offload changes placement,
# not values).
LADDER = ("remat", "accum_x2", "offload")


def device_capacity_mb(override_mb: float = 0,
                       device=None) -> Tuple[Optional[float], str]:
    """(per-device capacity MB or None, source) — source is one of
    "flag" (--hbm_cap_mb), "memory_stats" (bytes_limit), "device_table"
    (DEVICE_HBM_MB by kind), "unknown". None means no refusal can be
    grounded: the verdict must be "unknown", never a guess."""
    if override_mb:
        return float(override_mb), "flag"
    if device is None:
        try:
            import jax
            device = jax.local_devices()[0]
        except Exception:
            return None, "unknown"
    # guarded via xla_stats.memory_stat: some platforms return PARTIAL
    # dicts (bytes_in_use without bytes_limit) — a missing key must fall
    # through to the device table, never raise
    from mobilefinetuner_tpu.core.xla_stats import memory_stat
    limit = memory_stat(device, "bytes_limit", 0)
    if limit:
        return limit / 2 ** 20, "memory_stats"
    kind = str(getattr(device, "device_kind", "")).lower()
    for sub in sorted(DEVICE_HBM_MB, key=len, reverse=True):
        if sub in kind:
            return float(DEVICE_HBM_MB[sub]), "device_table"
    return None, "unknown"


@dataclasses.dataclass
class MemCheck:
    """One admission verdict. `event()` is the `mem_check` telemetry
    payload; `describe()` the human line the error/log carries."""
    est_mb: Optional[float]        # compiled peak + unaccounted live
    cap_mb: Optional[float]        # per-device capacity (None: unknown)
    verdict: str                   # "ok" | "over" | "unknown"
    phase: str = "preflight"       # preflight | dispatch | serve_build
    headroom: float = 0.1
    compiled_mb: Optional[float] = None   # XLA memory-analysis peak
    live_mb: Optional[float] = None       # bytes_in_use at check time
    cap_source: str = "unknown"

    @property
    def cap_frac(self) -> Optional[float]:
        """est / cap — the headline "how close to the ceiling" number
        (bench.py renders it next to peak_hbm_mb)."""
        if not self.est_mb or not self.cap_mb:
            return None
        return round(self.est_mb / self.cap_mb, 4)

    def event(self) -> dict:
        return {"est_mb": round(self.est_mb, 2) if self.est_mb else None,
                "cap_mb": round(self.cap_mb, 2) if self.cap_mb else None,
                "verdict": self.verdict, "phase": self.phase,
                "headroom": self.headroom, "cap_frac": self.cap_frac,
                "compiled_mb": (round(self.compiled_mb, 2)
                                if self.compiled_mb else None),
                "live_mb": (round(self.live_mb, 2)
                            if self.live_mb is not None else None),
                "cap_source": self.cap_source}

    def describe(self) -> str:
        est = f"{self.est_mb:.0f} MB" if self.est_mb else "unknown"
        cap = (f"{self.cap_mb:.0f} MB ({self.cap_source})"
               if self.cap_mb else "unknown")
        return (f"estimated {est} vs capacity {cap} under "
                f"{self.headroom:.0%} headroom -> {self.verdict}")


def _verdict(est_mb: Optional[float], cap_mb: Optional[float],
             headroom: float) -> str:
    if not est_mb or not cap_mb:
        return "unknown"
    return "over" if est_mb > cap_mb * (1.0 - headroom) else "ok"


def preflight(compiled, cap_mb: float = 0, headroom: float = 0.1,
              devices=None, phase: str = "preflight") -> MemCheck:
    """Admission check for a compiled executable: XLA's memory-analysis
    peak plus any live device bytes its own arguments do not cover
    (params already count as arguments — only the surplus beyond them
    is added, so nothing is double-billed), against per-device capacity
    under the headroom margin. Backends without memory analysis (or
    with no resolvable capacity) yield verdict "unknown": admission
    never refuses on a guess."""
    compiled_mb = compiled_peak_mb(compiled) if compiled is not None \
        else 0.0
    arg_mb = 0.0
    try:
        arg_mb = compiled.memory_analysis().argument_size_in_bytes / 2 ** 20
    except Exception:
        pass
    live = live_hbm_mb(devices)
    extra = max(live - arg_mb, 0.0) if live is not None else 0.0
    est = (compiled_mb + extra) if compiled_mb else None
    cap, source = device_capacity_mb(override_mb=cap_mb)
    return MemCheck(est_mb=est, cap_mb=cap,
                    verdict=_verdict(est, cap, headroom), phase=phase,
                    headroom=headroom, compiled_mb=compiled_mb or None,
                    live_mb=live, cap_source=source)


def analytic_check(est_mb: float, cap_mb: float = 0, headroom: float = 0.1,
                   phase: str = "serve_build") -> MemCheck:
    """Admission check from an ANALYTIC estimate (the serve engine's
    params + adapter bank + KV pool sum, computed before anything is
    allocated — a refusal must cost nothing)."""
    cap, source = device_capacity_mb(override_mb=cap_mb)
    return MemCheck(est_mb=float(est_mb), cap_mb=cap,
                    verdict=_verdict(est_mb, cap, headroom), phase=phase,
                    headroom=headroom, cap_source=source)


def is_resource_exhausted(err: BaseException) -> bool:
    """True for XLA's out-of-memory family (XlaRuntimeError carries the
    absl status name in its message) — the dispatch/compile signal the
    degradation ladder treats as a failed admission rather than a
    crash. Matched on the status text so the check needs no jaxlib
    import (and covers the injected simulation on CPU)."""
    return "RESOURCE_EXHAUSTED" in str(err)


_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def host_rss_mb() -> Optional[float]:
    """This process's resident set size in MB (Linux /proc/self/statm;
    None where unavailable) — the host-side pressure signal the
    prefetch producer's shed guard reads BEFORE the OS OOM-killer
    picks a victim (data/prefetch.py)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE / 2 ** 20
    except (OSError, ValueError, IndexError):
        return None
