"""Model configurations, parsed from HF `config.json`.

TPU-native analog of the reference's hand-rolled JSON field extraction
(reference: operators/finetune_ops/graph/gpt2_model.h:50-66 `GPT2Config`,
graph/gemma_model.h:17-43 `GemmaTextConfig`, both with `from_pretrained(dir)`).
We parse with the stdlib json module instead of hand-rolled string scanning,
but keep the same field surface + defaults so the same HF checkpoint dirs work.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional


def _load_config_json(model_dir: str) -> dict:
    path = os.path.join(model_dir, "config.json")
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


@dataclasses.dataclass
class GPT2Config:
    """GPT-2 family config (reference: graph/gpt2_model.h:50-66).

    Field names follow HF `config.json` for GPT-2 (n_embd/n_head/n_layer/...).
    """

    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    # "gelu_new" = tanh approximation; the reference's gelu matches HF
    # gelu_new / gelu_pytorch_tanh (reference: core/ops.cpp:1055-1062).
    activation_function: str = "gelu_new"
    embd_pdrop: float = 0.0
    resid_pdrop: float = 0.0
    attn_pdrop: float = 0.0
    tie_word_embeddings: bool = True
    # Attention impl: "auto" (flash from S >= 512 at D <= 128, S >= 2048
    # at D = 256; measured e2e on v5e — ops/attention.resolve_impl),
    # "flash" (Pallas kernel), "xla" (jnp reference).
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @classmethod
    def from_pretrained(cls, model_dir: str) -> "GPT2Config":
        raw = _load_config_json(model_dir)
        return cls(
            vocab_size=raw.get("vocab_size", 50257),
            n_positions=raw.get("n_positions", raw.get("n_ctx", 1024)),
            n_embd=raw.get("n_embd", 768),
            n_layer=raw.get("n_layer", 12),
            n_head=raw.get("n_head", 12),
            layer_norm_epsilon=raw.get("layer_norm_epsilon", 1e-5),
            activation_function=raw.get("activation_function", "gelu_new"),
            embd_pdrop=raw.get("embd_pdrop", 0.0),
            resid_pdrop=raw.get("resid_pdrop", 0.0),
            attn_pdrop=raw.get("attn_pdrop", 0.0),
            tie_word_embeddings=raw.get("tie_word_embeddings", True),
        )

    @classmethod
    def gpt2_small(cls) -> "GPT2Config":
        return cls()

    @classmethod
    def gpt2_medium(cls) -> "GPT2Config":
        return cls(n_embd=1024, n_layer=24, n_head=16)

    @classmethod
    def gpt2_large(cls) -> "GPT2Config":
        return cls(n_embd=1280, n_layer=36, n_head=20)

    @classmethod
    def gpt2_xl(cls) -> "GPT2Config":
        return cls(n_embd=1600, n_layer=48, n_head=25)

    @classmethod
    def tiny(cls, vocab_size: int = 257) -> "GPT2Config":
        """A tiny config for tests (fast CPU forward/backward)."""
        return cls(vocab_size=vocab_size, n_positions=64, n_embd=32,
                   n_layer=2, n_head=2)


@dataclasses.dataclass
class Gemma3TextConfig:
    """Gemma-3 text-decoder config (reference: graph/gemma_model.h:17-43).

    Defaults are the Gemma-3-270M text config. Key Gemma-3 specifics mirrored
    from the reference model graph (graph/gemma_model.cpp):
    - embeddings scaled by sqrt(hidden_size) (gemma_model.cpp:222-248)
    - GQA with num_key_value_heads < num_attention_heads
    - per-head q/k RMSNorm
    - dual RoPE theta: `rope_theta` (global layers) vs `rope_local_base_freq`
      (sliding-window layers), chosen per `layer_types[i]`
      (gemma_model.cpp:579-625)
    - 512-token sliding-window mask on local layers (gemma_model.h:26)
    - sandwich norms + (1+weight) RMSNorm semantics (core/ops.cpp:1489)
    - untied behavior: lm_head weight is tied to embeddings in HF Gemma-3.
    """

    vocab_size: int = 262144
    hidden_size: int = 640
    intermediate_size: int = 2048
    num_hidden_layers: int = 18
    num_attention_heads: int = 4
    num_key_value_heads: int = 1
    head_dim: int = 256
    max_position_embeddings: int = 32768
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1000000.0
    rope_local_base_freq: float = 10000.0
    sliding_window: int = 512
    # Per-layer attention type: "full_attention" | "sliding_attention".
    # Gemma-3 default pattern: 5 local : 1 global.
    layer_types: Optional[List[str]] = None
    query_pre_attn_scalar: float = 256.0
    hidden_activation: str = "gelu_pytorch_tanh"
    tie_word_embeddings: bool = True
    sliding_window_pattern: int = 6
    attention_impl: str = "auto"

    def __post_init__(self):
        if self.layer_types is None:
            p = self.sliding_window_pattern
            self.layer_types = [
                "full_attention" if (i + 1) % p == 0 else "sliding_attention"
                for i in range(self.num_hidden_layers)
            ]

    def is_global_layer(self, i: int) -> bool:
        return self.layer_types[i] == "full_attention"

    @classmethod
    def from_pretrained(cls, model_dir: str) -> "Gemma3TextConfig":
        raw = _load_config_json(model_dir)
        # Multimodal Gemma-3 checkpoints nest the text config.
        if "text_config" in raw:
            raw = raw["text_config"]
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name in raw:
                kw[f.name] = raw[f.name]
        return cls(**kw)

    @classmethod
    def gemma3_270m(cls) -> "Gemma3TextConfig":
        return cls()

    @classmethod
    def gemma3_1b(cls) -> "Gemma3TextConfig":
        return cls(hidden_size=1152, intermediate_size=6912,
                   num_hidden_layers=26, num_attention_heads=4,
                   num_key_value_heads=1, head_dim=256)

    @classmethod
    def tiny(cls, vocab_size: int = 300) -> "Gemma3TextConfig":
        """Tiny config for tests; keeps GQA + local/global interleave."""
        return cls(vocab_size=vocab_size, hidden_size=32,
                   intermediate_size=64, num_hidden_layers=4,
                   num_attention_heads=4, num_key_value_heads=2, head_dim=8,
                   max_position_embeddings=128, sliding_window=16,
                   query_pre_attn_scalar=8.0, sliding_window_pattern=3)
