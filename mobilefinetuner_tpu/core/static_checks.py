"""graftlint: repo-invariant static analysis + compiled-artifact contract
helpers (DESIGN.md §24).

Eighteen rounds of hardening produced a set of invariants this stack's
performance and correctness rest on — zero host syncs in the step loop,
donated buffers never touched after dispatch, f32 accumulation on every
adapter matmul, every emitted event present in EVENT_SCHEMA, lock
discipline in the threaded host subsystems. Until this module they were
enforced by scattered one-off pins (a jaxpr grep here, a source-regex
scan there), so every new module re-derived or silently skipped them.
This module makes them MECHANICAL:

  - an AST lint engine with a rule registry (`RULES`), per-line
    `# graftlint: disable=<rule>(<reason>)` suppressions, and a
    machine-readable finding model — driven by `tools/graft_lint.py`
    (text/JSON output, bench_compare-style exit codes, runs as a tier-1
    test over the whole package);
  - compiled-artifact helpers (`jaxpr_*`, `hlo_*`) that consolidate the
    hand-rolled jaxpr/HLO greps from tests/test_lora.py,
    test_lora_fused.py, test_telemetry.py behind one API — also the
    substrate of `tools/check_compiled_contracts.py`, which lowers
    representative train/decode/multitenant programs and pins retrace
    counts, a collective census, donation, and named-scope spans.

The lint half imports ONLY the stdlib (ast/tokenize/re) — linting must
never initialize a jax backend. The artifact helpers import jax lazily
inside each function.

Suppression grammar (one comment suppresses its own line; a comment
alone on a line suppresses the next line — for calls whose expression
spans lines, anchor the comment on the line the finding names):

    x = float(loss)  # graftlint: disable=sync-hazard(flush boundary)
    # graftlint: disable=sync-hazard(flush boundary),donation-hazard(why)

Every suppression must name a shipped rule AND carry a non-empty reason
— a bare `disable=<rule>` or an unknown rule name is itself a finding
(`bad-suppression`), so silent drift of the suppression inventory is
impossible.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Set, Tuple)

# ---------------------------------------------------------------------------
# configuration: which invariant applies where (paths are suffix-matched
# against the scanned file's repo-relative posix path)
# ---------------------------------------------------------------------------

#: modules whose code runs on (or is reachable from) the train/decode
#: step loop: a host sync here stalls the device pipeline. models/ and
#: ops/ are traced code — they must never be ABLE to sync.
STEP_LOOP_MODULES: Tuple[str, ...] = (
    "mobilefinetuner_tpu/train/trainer.py",
    "mobilefinetuner_tpu/serve/engine.py",
    "mobilefinetuner_tpu/multitenant/engine.py",
    "mobilefinetuner_tpu/cli/common.py",
    "mobilefinetuner_tpu/models/",
    "mobilefinetuner_tpu/ops/",
)

#: modules whose matmul/einsum chains feed training math: every
#: kwarg-capable contraction must pin its accumulation dtype. The infix
#: `@` operator is exempt BY DESIGN — it is the base-model forward's
#: compute-dtype path (bf16 base matmuls are intended); adapter/loss
#: math that needs f32 accumulation must use the kwarg-capable
#: spellings (jnp.einsum/matmul/dot/tensordot, lax.dot_general).
DTYPE_ACCUM_MODULES: Tuple[str, ...] = (
    "mobilefinetuner_tpu/models/",
    "mobilefinetuner_tpu/ops/",
)

#: the threaded host subsystems: each must DECLARE its cross-thread
#: shared state in a module-level GRAFT_SHARED_STATE literal, and every
#: declared guarded field must be touched only under its declared lock.
THREADED_MODULES: Tuple[str, ...] = (
    "mobilefinetuner_tpu/data/prefetch.py",
    "mobilefinetuner_tpu/io/async_ckpt.py",
    "mobilefinetuner_tpu/core/metrics_http.py",
    "mobilefinetuner_tpu/serve/engine.py",
    "mobilefinetuner_tpu/multitenant/engine.py",
    "tools/serve_router.py",
)

#: the zero-sync structural pin (was test_observability's source grep):
#: "never" = no jax import anywhere, even lazy; "toplevel" = module
#: level must stay jax-free (lazy in-function imports allowed).
NO_JAX_MODULES: Dict[str, str] = {
    "mobilefinetuner_tpu/core/metrics_http.py": "never",
    "mobilefinetuner_tpu/core/trace.py": "toplevel",
    "mobilefinetuner_tpu/core/telemetry.py": "toplevel",
}

#: step builders whose returned callable donates these positional args
#: (jax.jit(..., donate_argnums=...) calls are detected from their own
#: literal donate_argnums)
DONATING_BUILDERS: Dict[str, Tuple[int, ...]] = {
    "make_train_step": (0, 2),
    "make_multi_train_step": (0, 2),
}

#: modules scanned for serve-taxonomy phase=/reason= literals
SERVE_TAXONOMY_MODULES: Tuple[str, ...] = (
    "mobilefinetuner_tpu/serve/engine.py",
    "tools/serve_bench.py",
)


# ---------------------------------------------------------------------------
# finding + suppression model
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative posix path
    line: int          # 1-based
    col: int           # 0-based
    message: str
    suppressed: bool = False
    reason: str = ""   # the suppression's reason when suppressed

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tail = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}: {self.message}{tail}")


_SUPPRESS_RE = re.compile(r"graftlint:\s*disable=(.*)$")
_ITEM_RE = re.compile(r"\s*([a-z][a-z0-9-]*)\s*(?:\(([^()]*)\))?\s*$")


def _split_items(spec: str) -> List[str]:
    """Split `rule1(reason, with commas),rule2(...)` on TOP-LEVEL commas
    only — reasons are prose and may contain commas."""
    items, depth, cur = [], 0, []
    for ch in spec:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(depth - 1, 0)
        if ch == "," and depth == 0:
            items.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        items.append("".join(cur))
    return items


def parse_suppressions(source: str, path: str
                       ) -> Tuple[Dict[int, Dict[str, str]], List[Finding]]:
    """-> ({line: {rule: reason}}, malformed-suppression findings).

    A comment on a code line covers that line; a comment alone on its
    line covers the NEXT line. Missing reason / unparseable item =>
    `bad-suppression` finding (never silently honored)."""
    by_line: Dict[int, Dict[str, str]] = {}
    bad: List[Finding] = []
    try:
        toks = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return by_line, bad
    lines = source.splitlines()
    for tok in toks:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        lineno = tok.start[0]
        src_line = lines[lineno - 1] if lineno <= len(lines) else ""
        standalone = src_line.strip().startswith("#")
        target = lineno + 1 if standalone else lineno
        entry = by_line.setdefault(target, {})
        for item in _split_items(m.group(1)):
            im = _ITEM_RE.match(item)
            if not im or im.group(2) is None or not im.group(2).strip():
                bad.append(Finding(
                    "bad-suppression", path, lineno, tok.start[1],
                    f"malformed suppression {item.strip()!r}: grammar is "
                    f"disable=<rule>(<reason>), reason required"))
                continue
            name, reason = im.group(1), im.group(2).strip()
            if name not in RULES and name != "bad-suppression":
                bad.append(Finding(
                    "bad-suppression", path, lineno, tok.start[1],
                    f"suppression names unknown rule {name!r} "
                    f"(shipped: {', '.join(sorted(RULES))})"))
                continue
            entry[name] = reason
    return by_line, bad


# ---------------------------------------------------------------------------
# module / project model
# ---------------------------------------------------------------------------

class LintError(Exception):
    """Engine-level failure (unreadable path, syntax error): graft_lint
    exits 1 on these, distinct from findings (exit 2)."""


class Module:
    """One parsed source file + its suppression table."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.relpath = relpath.replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.source = f.read()
        try:
            self.tree = ast.parse(self.source, filename=relpath)
        except SyntaxError as e:
            raise LintError(f"{relpath}: syntax error: {e}") from e
        self.suppressions, self.bad_suppressions = parse_suppressions(
            self.source, self.relpath)

    def matches(self, suffixes: Iterable[str]) -> bool:
        return any(self.relpath.endswith(s) or
                   (s.endswith("/") and s.rstrip("/") + "/" in
                    "/" + self.relpath)
                   for s in suffixes)


class Project:
    """The scanned file set. `modules` are the files named on the CLI
    (fully linted); `aux_modules` are the sibling `tools/` sources that
    cross-file rules (emit-schema, serve-taxonomy) must see even when
    only the package directory was passed."""

    def __init__(self, paths: Sequence[str]):
        self.modules: List[Module] = []
        seen: Set[str] = set()
        roots: Set[str] = set()
        for p in paths:
            p = os.path.abspath(p)
            if os.path.isdir(p):
                files = sorted(
                    os.path.join(dp, f)
                    for dp, dns, fns in os.walk(p)
                    if "__pycache__" not in dp
                    for f in fns if f.endswith(".py"))
            elif os.path.isfile(p):
                files = [p]
            else:
                raise LintError(f"no such path: {p}")
            for f in files:
                if f not in seen:
                    seen.add(f)
                    self.modules.append(Module(f, self._rel(f)))
            roots.add(self._repo_root(p))
        self._seen = seen
        self.repo_root = sorted(roots)[0] if roots else os.getcwd()
        self.aux_modules: List[Module] = []
        tools = os.path.join(self.repo_root, "tools")
        if os.path.isdir(tools):
            for f in sorted(os.listdir(tools)):
                full = os.path.join(tools, f)
                if f.endswith(".py") and full not in seen:
                    try:
                        self.aux_modules.append(Module(full, self._rel(full)))
                    except LintError:
                        pass  # aux files never fail the run structurally

    @staticmethod
    def _repo_root(path: str) -> str:
        """Walk up to the directory that CONTAINS mobilefinetuner_tpu."""
        d = path if os.path.isdir(path) else os.path.dirname(path)
        while True:
            if os.path.isdir(os.path.join(d, "mobilefinetuner_tpu")):
                return d
            parent = os.path.dirname(d)
            if parent == d:
                return os.path.dirname(path) or os.getcwd()
            d = parent

    def _rel(self, abspath: str) -> str:
        root = self._repo_root(abspath)
        return os.path.relpath(abspath, root).replace(os.sep, "/")

    def all_modules(self) -> List[Module]:
        return self.modules + self.aux_modules


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'jnp.einsum' for Attribute chains over Names; None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def base_name(node: ast.AST) -> Optional[str]:
    """The root Name of an Attribute/Subscript/Call chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Starred)):
        node = node.value
    if isinstance(node, ast.Call):
        return base_name(node.func)
    if isinstance(node, ast.Name):
        return node.id
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _target_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


def _function_nodes(tree: ast.AST) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


# ---------------------------------------------------------------------------
# host-dataflow classification (sync-hazard's false-positive filter)
# ---------------------------------------------------------------------------

_HOST_BUILTINS = {"float", "int", "len", "round", "bool", "str", "repr",
                  "abs", "format"}
# builtins whose result is host iff every argument is host (sum() of
# DEVICE arrays is a device scalar, so these are conditional)
_HOST_IF_ARGS = {"sum", "min", "max", "sorted", "any", "all", "list",
                 "tuple", "dict", "set", "zip", "enumerate"}
_HOST_ROOTS = {"np", "numpy", "os", "time", "math", "json", "re",
               "statistics", "collections", "dataclasses", "itertools"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize", "nbytes"}
_HOST_METHODS = {"item", "tolist", "keys", "values", "items", "qsize",
                 "split", "strip", "join", "get_nowait"}


def _is_host_expr(node: ast.AST, host: Set[str]) -> bool:
    """True when `node` is statically known to produce HOST data (so a
    float()/np.asarray over it cannot be a device sync). Conservative:
    unknown => False (flag it; an intentional sync gets a suppression)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in host
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return True
        return _is_host_expr(node.value, host)
    if isinstance(node, ast.Subscript):
        return _is_host_expr(node.value, host)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _HOST_BUILTINS:
            return True
        if isinstance(fn, ast.Name) and fn.id in _HOST_IF_ARGS:
            return bool(node.args) and all(
                _is_host_expr(a, host) for a in node.args)
        d = dotted_name(fn)
        if d:
            root = d.split(".")[0]
            if root in _HOST_ROOTS:
                return True
            if d.endswith("device_get"):
                return True
        if isinstance(fn, ast.Attribute):
            if fn.attr in _HOST_METHODS:
                return True
            if _is_host_expr(fn.value, host):
                return True  # method on a host object stays host
        return False
    if isinstance(node, (ast.BinOp,)):
        return _is_host_expr(node.left, host) and \
            _is_host_expr(node.right, host)
    if isinstance(node, ast.UnaryOp):
        return _is_host_expr(node.operand, host)
    if isinstance(node, ast.BoolOp):
        return all(_is_host_expr(v, host) for v in node.values)
    if isinstance(node, ast.Compare):
        return _is_host_expr(node.left, host) and \
            all(_is_host_expr(c, host) for c in node.comparators)
    if isinstance(node, ast.IfExp):
        return _is_host_expr(node.body, host) and \
            _is_host_expr(node.orelse, host)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return all(_is_host_expr(e, host) for e in node.elts)
    if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
        return True
    if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
        return _is_host_expr(node.elt, host)
    return False


def _collect_host_names(fn: ast.AST) -> Set[str]:
    """Names inside `fn` bound from host-producing expressions (a few
    fixpoint passes so chains like `h = device_get(x); v = h[0]`
    propagate)."""
    host: Set[str] = set()
    for _ in range(3):
        before = len(host)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if _is_host_expr(node.value, host):
                    for t in node.targets:
                        host.update(_target_names(t))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if _is_host_expr(node.value, host):
                    host.update(_target_names(node.target))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                it = node.iter
                host_iter = _is_host_expr(it, host)
                if isinstance(it, ast.Call):
                    d = dotted_name(it.func)
                    if d in ("zip", "enumerate", "range", "sorted",
                             "reversed"):
                        host_iter = host_iter or any(
                            _is_host_expr(a, host) for a in it.args)
                if host_iter:
                    host.update(_target_names(node.target))
            elif isinstance(node, ast.comprehension):
                if _is_host_expr(node.iter, host):
                    host.update(_target_names(node.target))
            elif isinstance(node, ast.withitem) and \
                    node.optional_vars is not None:
                if _is_host_expr(node.context_expr, host):
                    host.update(_target_names(node.optional_vars))
        if len(host) == before:
            break
    return host


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Rule:
    name: str
    doc: str
    fn: Callable[[Project], Iterator[Finding]]


RULES: Dict[str, Rule] = {}


def rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = Rule(name, doc, fn)
        return fn
    return deco


# ---------------------------------------------------------------------------
# rule: sync-hazard
# ---------------------------------------------------------------------------

@rule("sync-hazard",
      "host-sync call (float()/.item()/np.asarray/device_get/"
      "block_until_ready) reachable from a step-loop module")
def _rule_sync_hazard(project: Project) -> Iterator[Finding]:
    for mod in project.modules:
        if not mod.matches(STEP_LOOP_MODULES):
            continue
        funcs = _function_nodes(mod.tree)
        # map each call node to its innermost enclosing function
        owner: Dict[ast.AST, ast.AST] = {}
        for fn in funcs:
            for sub in ast.walk(fn):
                owner[sub] = fn  # innermost wins: funcs walk outer->inner?
        # ensure innermost wins: walk functions by position (outer first),
        # later (inner) assignments overwrite
        host_of: Dict[ast.AST, Set[str]] = {
            fn: _collect_host_names(fn) for fn in funcs}
        # nested defs read closure variables: a name host in an ancestor
        # scope is host in the child (funcs is outer-first, so parents
        # are resolved before children)
        parent: Dict[ast.AST, ast.AST] = {}
        for fn in funcs:
            for sub in ast.walk(fn):
                if sub is not fn and sub in host_of:
                    parent[sub] = fn  # innermost enclosing wins (later
                    #                   overwrites walk outer->inner)
        for fn in funcs:
            p = parent.get(fn)
            if p is not None:
                host_of[fn] = host_of[fn] | host_of[p]
        # `x = np.asarray(x)` must not launder x into the host set for
        # its own check: map every call to the names ITS OWN statement
        # assigns, and ignore those names as host evidence at that site
        self_targets: Dict[int, Set[str]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                names: Set[str] = set()
                for t in node.targets:
                    names.update(_target_names(t))
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Call):
                        self_targets[id(sub)] = names
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = None
            arg = node.args[0] if node.args else None
            fn_expr = node.func
            if isinstance(fn_expr, ast.Name) and fn_expr.id == "float":
                kind = "float()"
            elif isinstance(fn_expr, ast.Attribute):
                d = dotted_name(fn_expr) or ""
                if fn_expr.attr == "item" and not node.args:
                    kind, arg = ".item()", fn_expr.value
                elif d in ("np.asarray", "numpy.asarray"):
                    kind = "np.asarray"
                elif fn_expr.attr == "device_get":
                    kind, arg = "device_get", None
                elif fn_expr.attr == "block_until_ready":
                    kind, arg = "block_until_ready", None
            if kind is None:
                continue
            enclosing = owner.get(node)
            host = host_of.get(enclosing, set()) if enclosing is not None \
                else set()
            host = host - self_targets.get(id(node), set())
            if kind in ("float()", ".item()", "np.asarray") and \
                    arg is not None and _is_host_expr(arg, host):
                continue  # host-side conversion, not a device sync
            if enclosing is None and kind in ("float()", "np.asarray"):
                continue  # module-level constants are host by definition
            yield Finding(
                "sync-hazard", mod.relpath, node.lineno, node.col_offset,
                f"{kind} in step-loop module: forces a device->host sync "
                f"on the hot path (move it behind the buffered-metrics "
                f"flush, or suppress with the reason it is intentional)")


# ---------------------------------------------------------------------------
# rule: donation-hazard
# ---------------------------------------------------------------------------

def _donate_literal(node: ast.expr) -> Optional[Tuple[int, ...]]:
    """Positions from a donate_argnums expression. Conditional
    spellings like `(2, 3) if donate else ()` (the engines' CPU
    opt-out) contribute the UNION of both branches — on the platform
    where donation is live, those positions are donated."""
    if isinstance(node, ast.IfExp):
        a = _donate_literal(node.body) or ()
        b = _donate_literal(node.orelse) or ()
        return tuple(sorted(set(a) | set(b))) or None
    try:
        val = ast.literal_eval(node)
    except ValueError:
        return None
    if isinstance(val, int):
        return (val,)
    if isinstance(val, (tuple, list)):
        return tuple(int(v) for v in val)
    return None


def _donating_positions(call: ast.Call,
                        donating: Dict[str, Tuple[int, ...]]
                        ) -> Optional[Tuple[int, ...]]:
    """Donated positional-arg indices for a call that BUILDS a step
    (make_train_step / jax.jit(..., donate_argnums=...)), else None."""
    d = dotted_name(call.func)
    leaf = d.split(".")[-1] if d else None
    if leaf in donating:
        dn = _kwarg(call, "donate")
        if dn is not None and isinstance(dn, ast.Constant) and not dn.value:
            return None
        return donating[leaf]
    if d in ("jax.jit", "jit"):
        dn = _kwarg(call, "donate_argnums")
        if dn is None:
            return None
        return _donate_literal(dn)
    return None


def _ref_path(node: ast.AST) -> Optional[str]:
    """A trackable reference path: a bare Name ('pool_k') or a
    self-attribute chain ('self.pool_k'). Anything else — subscripts,
    calls, non-self attributes — is not tracked."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        d = dotted_name(node)
        if d is not None and d.startswith("self."):
            return d
    return None


def _target_paths(target: ast.expr) -> List[str]:
    """_target_names extended with self-attribute targets, for the
    donation rule: `self.pool_k, self.pool_v = ...` rebinds both."""
    p = _ref_path(target)
    if p is not None:
        return [p]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(_target_paths(elt))
        return out
    if isinstance(target, ast.Starred):
        return _target_paths(target.value)
    return []


@rule("donation-hazard",
      "a donated argument's name is referenced after the dispatching "
      "call without rebinding (the buffer no longer exists)")
def _rule_donation_hazard(project: Project) -> Iterator[Finding]:
    for mod in project.modules:
        donating: Dict[str, Tuple[int, ...]] = dict(DONATING_BUILDERS)
        # local builders that RETURN a donating builder's call
        for fn in _function_nodes(mod.tree):
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and \
                        isinstance(node.value, ast.Call):
                    pos = _donating_positions(node.value, donating)
                    if pos is not None:
                        donating[fn.name] = pos
        # self-attribute step bindings are MODULE-wide: the engines
        # bind `self._step = jax.jit(..., donate_argnums=...)` in a
        # builder method and dispatch from another (serve decode,
        # multitenant admit)
        selfsteps: Dict[str, Tuple[int, ...]] = {}
        for fn in _function_nodes(mod.tree):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    pos = _donating_positions(node.value, donating)
                    if pos is None:
                        continue
                    for t in node.targets:
                        for path in _target_paths(t):
                            if path.startswith("self."):
                                selfsteps[path] = pos
        for fn in _function_nodes(mod.tree):
            yield from _scan_donation_scope(mod, fn, donating, selfsteps)


def _scan_donation_scope(mod: Module, fn: ast.AST,
                         donating: Dict[str, Tuple[int, ...]],
                         selfsteps: Optional[Dict[str, Tuple[int, ...]]]
                         = None) -> Iterator[Finding]:
    """Linear-order scan of one function body: find step-building
    assignments, then dispatching calls, then post-call reads of the
    donated names. Lexical order approximates execution order — good
    enough for the loop-shaped code this repo writes, and the rule's
    fixtures pin exactly that shape. Names are tracked as paths: bare
    locals AND self-attribute chains (`self._step` bindings, donated
    `self.pool_k` args — the serve/multitenant engine pattern)."""
    stepfns: Dict[str, Tuple[int, ...]] = dict(selfsteps or {})
    # pass 1: which local names hold donating callables
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donating_positions(node.value, donating)
            call = node.value
            if pos is None:
                # propagate through .lower(...).compile() chains
                f = call.func
                if isinstance(f, ast.Attribute) and \
                        f.attr in ("compile", "lower"):
                    root = base_name(f.value)
                    if root in stepfns:
                        pos = stepfns[root]
            if pos is not None:
                for t in node.targets:
                    for path in _target_paths(t):
                        stepfns[path] = pos
    if not stepfns:
        return
    # pass 2: dispatch sites and post-dispatch reads, in source order.
    # A dispatch's liveness starts at its END line, so the donated
    # args of a multi-line call are not their own post-call reads.
    events: List[Tuple[int, str, Any]] = []  # (line, kind, payload)
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            cpath = _ref_path(node.func)
            if cpath is not None and cpath in stepfns:
                donated = []
                for i in stepfns[cpath]:
                    if i < len(node.args):
                        p = _ref_path(node.args[i])
                        if p is not None:
                            donated.append(p)
                if donated:
                    end = getattr(node, "end_lineno", None) or node.lineno
                    events.append((end, "dispatch", (node, set(donated))))
    if not events:
        return
    # rebindings + reads
    for node in ast.walk(fn):
        path = _ref_path(node)
        if path is None:
            continue
        ctx = getattr(node, "ctx", None)
        if isinstance(ctx, ast.Store):
            events.append((node.lineno, "store", path))
        elif isinstance(ctx, ast.Load):
            events.append((node.lineno, "load", (node, path)))
    events.sort(key=lambda e: (e[0], 0 if e[1] == "dispatch" else 1))
    live: Dict[str, int] = {}  # donated path -> dispatch end line
    reported: Set[Tuple[str, int]] = set()
    for line, kind, payload in events:
        if kind == "dispatch":
            node, names = payload
            # names rebound by the dispatch's own assignment stay valid
            assign_targets: Set[str] = set()
            parent = _assign_parent(fn, node)
            if parent is not None:
                for t in parent.targets:
                    assign_targets.update(_target_paths(t))
            for n in names - assign_targets:
                live[n] = line
        elif kind == "store":
            live.pop(payload, None)
        elif kind == "load":
            name_node, n = payload
            if n in live and name_node.lineno > live[n]:
                key = (n, name_node.lineno)
                if key not in reported:
                    reported.add(key)
                    yield Finding(
                        "donation-hazard", mod.relpath, name_node.lineno,
                        name_node.col_offset,
                        f"{n!r} was donated to the step dispatched at "
                        f"line {live[n]} and is read afterwards without "
                        f"rebinding — the buffer has been consumed")


def _assign_parent(fn: ast.AST, call: ast.Call) -> Optional[ast.Assign]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and node.value is call:
            return node
    return None


# ---------------------------------------------------------------------------
# rule: untraced-branch
# ---------------------------------------------------------------------------

def _static_args_of(jit_call: Optional[ast.Call],
                    fn: ast.AST) -> Set[str]:
    """Param names made static by a jit call's static_argnames /
    static_argnums (literal values only)."""
    static: Set[str] = set()
    if jit_call is None:
        return static
    args = fn.args
    ordered = [a.arg for a in args.posonlyargs + args.args]
    sa = _kwarg(jit_call, "static_argnames")
    if sa is not None:
        try:
            v = ast.literal_eval(sa)
            static.update([v] if isinstance(v, str) else v)
        except ValueError:
            pass
    sn = _kwarg(jit_call, "static_argnums")
    if sn is not None:
        try:
            v = ast.literal_eval(sn)
            for i in ([v] if isinstance(v, int) else v):
                if 0 <= i < len(ordered):
                    static.add(ordered[i])
        except ValueError:
            pass
    return static


def _jitted_functions(mod: Module) -> Dict[str, Tuple[ast.AST, Set[str]]]:
    """{name: (FunctionDef, static_param_names)} for functions that are
    jitted: decorated with jax.jit (bare or via partial), or passed by
    name to a jax.jit(...) call in this module."""
    defs = {fn.name: fn for fn in _function_nodes(mod.tree)}
    jitted: Dict[str, Tuple[ast.AST, Set[str]]] = {}
    for name, fn in defs.items():
        for dec in fn.decorator_list:
            d = dotted_name(dec)
            if d in ("jax.jit", "jit"):
                jitted[name] = (fn, set())
            elif isinstance(dec, ast.Call):
                dd = dotted_name(dec.func)
                if dd in ("jax.jit", "jit"):
                    jitted[name] = (fn, _static_args_of(dec, fn))
                elif dd in ("partial", "functools.partial") and dec.args:
                    if dotted_name(dec.args[0]) in ("jax.jit", "jit"):
                        jitted[name] = (fn, _static_args_of(dec, fn))
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                dotted_name(node.func) in ("jax.jit", "jit") and node.args:
            tgt = node.args[0]
            if isinstance(tgt, ast.Name) and tgt.id in defs:
                jitted[tgt.id] = (defs[tgt.id],
                                  _static_args_of(node, defs[tgt.id]))
    return jitted


def _tracer_names_in_test(test: ast.AST, params: Set[str]) -> List[str]:
    """Parameter names the branch condition reads as VALUES (static
    shape/dtype reads, is-None checks, isinstance, and len() are
    exempt — they are trace-time constants)."""
    hits: List[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return
        if isinstance(node, ast.Call):
            d = dotted_name(node.func)
            if d in ("isinstance", "hasattr", "callable", "len",
                     "getattr"):
                return
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                    for op in node.ops):
            # `x is None` and `"key" in tree` read pytree STRUCTURE —
            # trace-time constants, not tracer values
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in params:
            hits.append(node.id)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(test)
    return hits


@rule("untraced-branch",
      "Python `if`/`while` on a tracer-valued expression inside a "
      "jitted function (the branch is taken at TRACE time, silently)")
def _rule_untraced_branch(project: Project) -> Iterator[Finding]:
    for mod in project.modules:
        for name, (fn, static) in _jitted_functions(mod).items():
            args = fn.args
            params = {a.arg for a in
                      args.posonlyargs + args.args + args.kwonlyargs
                      } - static
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    hits = _tracer_names_in_test(node.test, params)
                    if hits:
                        yield Finding(
                            "untraced-branch", mod.relpath, node.lineno,
                            node.col_offset,
                            f"branch on tracer-valued {sorted(set(hits))} "
                            f"inside jitted {name!r}: the Python branch "
                            f"freezes one side at trace time — use "
                            f"jnp.where/lax.cond, or mark the argument "
                            f"static")


# ---------------------------------------------------------------------------
# rule: dtype-accum
# ---------------------------------------------------------------------------

_ACCUM_FUNCS = ("einsum", "matmul", "dot", "tensordot", "dot_general")


@rule("dtype-accum",
      "matmul/einsum in models//ops/ without preferred_element_type "
      "(accumulation dtype silently follows the input dtype)")
def _rule_dtype_accum(project: Project) -> Iterator[Finding]:
    for mod in project.modules:
        if not mod.matches(DTYPE_ACCUM_MODULES):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if not d:
                continue
            parts = d.split(".")
            if parts[-1] not in _ACCUM_FUNCS:
                continue
            if parts[0] not in ("jnp", "jax", "lax", "numpy") and \
                    len(parts) > 1:
                continue
            if len(parts) == 1:  # bare einsum(...) — a local helper
                continue
            if parts[0] == "numpy" or parts[0] == "np":
                continue  # host-side numpy math is not device accumulation
            if _kwarg(node, "preferred_element_type") is None:
                yield Finding(
                    "dtype-accum", mod.relpath, node.lineno,
                    node.col_offset,
                    f"{d} without preferred_element_type: on bf16 inputs "
                    f"the accumulator silently degrades to bf16 — pin it "
                    f"(jnp.float32) or suppress with the reason the "
                    f"input dtype is already the accumulation dtype")


# ---------------------------------------------------------------------------
# rule: emit-schema (+ serve-taxonomy)
# ---------------------------------------------------------------------------

def collect_emit_sites(modules: Iterable[Module]
                       ) -> Dict[str, List[Tuple[str, int]]]:
    """{event_name: [(relpath, line), ...]} for every `.emit("x", ...)`
    and `event="x"` literal across the given modules."""
    found: Dict[str, List[Tuple[str, int]]] = {}
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "emit" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                found.setdefault(node.args[0].value, []).append(
                    (mod.relpath, node.lineno))
            ev = _kwarg(node, "event")
            if ev is not None and isinstance(ev, ast.Constant) and \
                    isinstance(ev.value, str):
                found.setdefault(ev.value, []).append(
                    (mod.relpath, node.lineno))
    return found


def _schema_key_lines(project: Project, const_name: str) -> Dict[str, int]:
    """{key: line} of a dict-literal constant in core/telemetry.py (to
    anchor never-emitted findings at their declaration)."""
    for mod in project.all_modules():
        if not mod.relpath.endswith("core/telemetry.py"):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and \
                    any(isinstance(t, ast.Name) and t.id == const_name
                        for t in node.targets) and \
                    isinstance(node.value, ast.Dict):
                return {k.value: k.lineno for k in node.value.keys
                        if isinstance(k, ast.Constant)}
    return {}


@rule("emit-schema",
      "telemetry emit sites and EVENT_SCHEMA must agree in BOTH "
      "directions (no unknown events emitted, no dead taxonomy)")
def _rule_emit_schema(project: Project) -> Iterator[Finding]:
    from mobilefinetuner_tpu.core.telemetry import EVENT_SCHEMA
    found = collect_emit_sites(project.all_modules())
    key_lines = _schema_key_lines(project, "EVENT_SCHEMA")
    for name, sites in sorted(found.items()):
        if name not in EVENT_SCHEMA:
            path, line = sites[0]
            yield Finding(
                "emit-schema", path, line, 0,
                f"emitted event {name!r} is not declared in EVENT_SCHEMA "
                f"(core/telemetry.py) — every event must land in the "
                f"schema + validator before it ships")
    # the dead-taxonomy direction only makes sense over a scan that
    # includes the schema's home module — a partial lint (one
    # subpackage, a fixture project) must not report every event it
    # happens not to contain as dead
    if not key_lines:
        return
    anchor = "mobilefinetuner_tpu/core/telemetry.py"
    for name in sorted(set(EVENT_SCHEMA) - set(found)):
        yield Finding(
            "emit-schema", anchor, key_lines.get(name, 1), 0,
            f"EVENT_SCHEMA declares {name!r} but no source ever emits it "
            f"(dead taxonomy) — wire the event or drop the entry")


@rule("emit-fields",
      "a literal-kwarg emit site must carry every REQUIRED field of "
      "its event's EVENT_SCHEMA entry (splat sites are validated at "
      "runtime by validate_event; this catches the static half — a "
      "field dropped at the call site would otherwise only surface "
      "when a reader validates the stream)")
def _rule_emit_fields(project: Project) -> Iterator[Finding]:
    from mobilefinetuner_tpu.core.telemetry import (EVENT_SCHEMA,
                                                    OPTIONAL_FIELDS)
    for mod in project.all_modules():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            ev = node.args[0].value
            if ev not in EVENT_SCHEMA:
                continue  # emit-schema already reports unknown events
            if any(kw.arg is None for kw in node.keywords):
                continue  # **payload splat: runtime validate_event's job
            provided = {kw.arg for kw in node.keywords}
            required = set(EVENT_SCHEMA[ev]) \
                - set(OPTIONAL_FIELDS.get(ev, ()))
            missing = sorted(required - provided)
            if missing:
                yield Finding(
                    "emit-fields", mod.relpath, node.lineno, 0,
                    f"emit({ev!r}) missing required schema field(s) "
                    f"{', '.join(missing)} — EVENT_SCHEMA is a floor; "
                    f"a None must be passed explicitly, not dropped")


_SNAKE = re.compile(r"^[a-z_]+$")


@rule("serve-taxonomy",
      "request lifecycle phase=/reason= literals in the serve layer "
      "must match REQUEST_PHASES/REQUEST_REASONS, both directions")
def _rule_serve_taxonomy(project: Project) -> Iterator[Finding]:
    from mobilefinetuner_tpu.core.telemetry import (REQUEST_PHASES,
                                                    REQUEST_REASONS)
    mods = [m for m in project.all_modules()
            if m.matches(SERVE_TAXONOMY_MODULES)]
    if not mods:
        return
    phases: Dict[str, Tuple[str, int]] = {}
    reasons: Dict[str, Tuple[str, int]] = {}
    for mod in mods:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            for kw, sink in (("phase", phases), ("reason", reasons)):
                v = _kwarg(node, kw)
                if v is not None and isinstance(v, ast.Constant) and \
                        isinstance(v.value, str) and _SNAKE.match(v.value):
                    sink.setdefault(v.value, (mod.relpath, node.lineno))
    anchor = mods[0].relpath
    for name, (path, line) in sorted(phases.items()):
        if name not in REQUEST_PHASES:
            yield Finding("serve-taxonomy", path, line, 0,
                          f"request phase {name!r} not in REQUEST_PHASES")
    for name in sorted(set(REQUEST_PHASES) - set(phases)):
        yield Finding("serve-taxonomy", anchor, 1, 0,
                      f"REQUEST_PHASES declares {name!r} but no serve "
                      f"emit site uses it (dead taxonomy)")
    for name, (path, line) in sorted(reasons.items()):
        if name not in REQUEST_REASONS:
            yield Finding("serve-taxonomy", path, line, 0,
                          f"request reason {name!r} not in REQUEST_REASONS")
    for name in sorted(set(REQUEST_REASONS) - set(reasons)):
        yield Finding("serve-taxonomy", anchor, 1, 0,
                      f"REQUEST_REASONS declares {name!r} but no serve "
                      f"emit site uses it (dead taxonomy)")


# ---------------------------------------------------------------------------
# rule: lock-discipline
# ---------------------------------------------------------------------------

def _shared_state_decl(mod: Module) -> Optional[dict]:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and \
                any(isinstance(t, ast.Name) and t.id == "GRAFT_SHARED_STATE"
                    for t in node.targets):
            try:
                val = ast.literal_eval(node.value)
            except ValueError:
                return None
            return val if isinstance(val, dict) else None
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _scan_lock_method(mod: Module, cls_name: str, method: ast.AST,
                      lock: str, guarded: Set[str], helpers: Set[str]
                      ) -> Iterator[Finding]:
    """Flag guarded-field accesses / locked-helper calls outside
    `with self.<lock>` within one method body."""

    def visit(node: ast.AST, under: bool) -> Iterator[Finding]:
        if isinstance(node, ast.With):
            is_lock = any(
                _self_attr(item.context_expr) == lock
                for item in node.items)
            for item in node.items:
                yield from visit(item.context_expr, under)
            for child in node.body:
                yield from visit(child, under or is_lock)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                yield from visit(child, False)  # closures run later
            return
        attr = _self_attr(node)
        if attr in guarded and not under:
            yield Finding(
                "lock-discipline", mod.relpath, node.lineno,
                node.col_offset,
                f"{cls_name}.{attr} is declared guarded by "
                f"self.{lock} but accessed outside it in "
                f"{getattr(method, 'name', '?')}()")
            return  # one finding per access site
        if isinstance(node, ast.Call):
            fattr = _self_attr(node.func)
            if fattr in helpers and not under:
                yield Finding(
                    "lock-discipline", mod.relpath, node.lineno,
                    node.col_offset,
                    f"{cls_name}.{fattr}() requires self.{lock} held "
                    f"(declared locked helper) but is called outside it "
                    f"in {getattr(method, 'name', '?')}()")
        for child in ast.iter_child_nodes(node):
            yield from visit(child, under)

    for stmt in method.body:
        yield from visit(stmt, False)


@rule("lock-discipline",
      "threaded host subsystems must declare their cross-thread state "
      "(GRAFT_SHARED_STATE) and touch guarded fields only under the "
      "declared lock")
def _rule_lock_discipline(project: Project) -> Iterator[Finding]:
    for mod in project.modules:
        if not mod.matches(THREADED_MODULES):
            continue
        decl = _shared_state_decl(mod)
        if decl is None:
            yield Finding(
                "lock-discipline", mod.relpath, 1, 0,
                "threaded module has no GRAFT_SHARED_STATE declaration "
                "(a literal dict: {class: {'lock': attr|None, 'guarded': "
                "[...], 'locked_helpers': [...], 'channels': [...], "
                "'note': ...}})")
            continue
        classes = {n.name: n for n in ast.walk(mod.tree)
                   if isinstance(n, ast.ClassDef)}
        for cls_name, spec in decl.items():
            if cls_name not in classes:
                yield Finding(
                    "lock-discipline", mod.relpath, 1, 0,
                    f"GRAFT_SHARED_STATE names unknown class {cls_name!r}")
                continue
            if not isinstance(spec, dict):
                yield Finding(
                    "lock-discipline", mod.relpath, 1, 0,
                    f"GRAFT_SHARED_STATE[{cls_name!r}] must be a dict")
                continue
            lock = spec.get("lock")
            guarded = set(spec.get("guarded", ()) or ())
            helpers = set(spec.get("locked_helpers", ()) or ())
            if lock is None:
                if guarded or helpers:
                    yield Finding(
                        "lock-discipline", mod.relpath, 1, 0,
                        f"{cls_name}: guarded fields declared but lock is "
                        f"None — name the lock or move the fields to "
                        f"'channels'")
                continue
            cls = classes[cls_name]
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name in ("__init__", "__del__") or \
                        method.name in helpers:
                    continue
                yield from _scan_lock_method(
                    mod, cls_name, method, lock, guarded, helpers)


# ---------------------------------------------------------------------------
# rule: no-jax-import
# ---------------------------------------------------------------------------

def _is_jax_import(node: ast.AST) -> bool:
    if isinstance(node, ast.Import):
        return any(a.name == "jax" or a.name.startswith("jax.")
                   for a in node.names)
    if isinstance(node, ast.ImportFrom):
        m = node.module or ""
        return m == "jax" or m.startswith("jax.")
    return False


@rule("no-jax-import",
      "zero-sync observability modules must not import jax (scrape/emit "
      "paths must be structurally unable to touch a device)")
def _rule_no_jax_import(project: Project) -> Iterator[Finding]:
    for mod in project.modules:
        policy = next((p for s, p in NO_JAX_MODULES.items()
                       if mod.relpath.endswith(s)), None)
        if policy is None:
            continue
        if policy == "never":
            nodes: List[ast.AST] = list(ast.walk(mod.tree))
        else:
            # toplevel = everything that executes at import time: the
            # module body INCLUDING statements nested in try/if/with
            # (the `try: import jax` idiom is still a module-level
            # import, and a class body executes at import time) — only
            # function bodies are deferred
            nodes = []
            stack: List[ast.AST] = list(mod.tree.body)
            while stack:
                n = stack.pop()
                nodes.append(n)
                if not isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    stack.extend(ast.iter_child_nodes(n))
        for node in nodes:
            if _is_jax_import(node):
                yield Finding(
                    "no-jax-import", mod.relpath, node.lineno,
                    node.col_offset,
                    f"jax import in a zero-sync module "
                    f"(policy: {policy}) — this code runs on scrape/emit "
                    f"hot paths and must not be able to touch a device")


# ---------------------------------------------------------------------------
# engine entry
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # unsuppressed, the exit-2 set
    suppressed: List[Finding]
    files: int
    rules: List[str]

    def to_dict(self) -> dict:
        return {
            "files": self.files,
            "rules": self.rules,
            "counts": {
                "findings": len(self.findings),
                "suppressed": len(self.suppressed),
            },
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
        }


def run_lint(paths: Sequence[str],
             rules: Optional[Sequence[str]] = None) -> LintResult:
    """Run the selected rules (default: all) over `paths`. Raises
    LintError on unreadable paths / syntax errors."""
    project = Project(paths)
    selected = list(RULES) if rules is None else list(rules)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise LintError(f"unknown rule(s): {', '.join(unknown)} "
                        f"(shipped: {', '.join(sorted(RULES))})")
    supp_by_path = {m.relpath: m.suppressions for m in project.modules}
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    # malformed suppressions are findings themselves (only for primary
    # modules — aux tools files are read for cross-file scans only)
    for mod in project.modules:
        findings.extend(mod.bad_suppressions)
    for name in selected:
        for f in RULES[name].fn(project):
            table = supp_by_path.get(f.path, {})
            reason = table.get(f.line, {}).get(f.rule)
            if reason is not None:
                f.suppressed, f.reason = True, reason
                suppressed.append(f)
            else:
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings, suppressed, len(project.modules), selected)


# ===========================================================================
# compiled-artifact helpers: the one API behind the old jaxpr/HLO greps
# (jax imported lazily — the lint half above must stay jax-free)
# ===========================================================================

def _iter_eqns(jaxpr) -> Iterator:
    """All equations of a (Closed)Jaxpr INCLUDING sub-jaxprs (scan/cond
    bodies, custom_vjp call jaxprs, pallas kernels ride in params)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for val in eqn.params.values():
            yield from _iter_param_jaxprs(val)


def _iter_param_jaxprs(val) -> Iterator:
    if hasattr(val, "eqns") or hasattr(val, "jaxpr"):
        yield from _iter_eqns(val)
    elif isinstance(val, (tuple, list)):
        for v in val:
            yield from _iter_param_jaxprs(v)


def jaxpr_primitive_counts(fn, *args, **kwargs) -> Dict[str, int]:
    """{primitive_name: count} over fn's jaxpr, sub-jaxprs included."""
    import jax
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    counts: Dict[str, int] = {}
    for eqn in _iter_eqns(jaxpr):
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
    return counts


def jaxpr_contains(fn, primitive: str, *args, **kwargs) -> bool:
    return jaxpr_primitive_counts(fn, *args, **kwargs).get(primitive, 0) > 0


def jaxpr_dot_census(fn, *args, **kwargs) -> List[dict]:
    """One entry per dot_general in fn's jaxpr (sub-jaxprs included):
    {"preferred_element_type": numpy-dtype-or-None}. The structural spine
    of the f32-accumulation pins (CPU emulates bf16 matmuls in f32, so a
    numeric-only check is vacuous — the jaxpr param is the contract)."""
    import jax
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    out = []
    for eqn in _iter_eqns(jaxpr):
        if eqn.primitive.name == "dot_general":
            out.append({"preferred_element_type":
                        eqn.params.get("preferred_element_type")})
    return out


def assert_dots_accumulate_f32(fn, *args, min_dots: int = 1, **kwargs):
    """Every dot_general in fn's jaxpr must carry
    preferred_element_type=float32; at least `min_dots` must exist."""
    import numpy as np
    dots = jaxpr_dot_census(fn, *args, **kwargs)
    assert len(dots) >= min_dots, \
        f"expected >= {min_dots} dot_general eqns, found {len(dots)}"
    for i, d in enumerate(dots):
        pet = d["preferred_element_type"]
        assert pet is not None and np.dtype(pet) == np.float32, \
            f"dot_general #{i} accumulates in {pet} (want float32)"


_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_ALIAS_RE = re.compile(r"\{[\d,\s]*\}\s*:\s*\(\s*\d+\s*,\s*\{[^}]*\}\s*"
                       r"(?:,\s*(?:may|must)-alias\s*)?\)")
_COLLECTIVE_KINDS = ("all-gather", "all-reduce", "reduce-scatter",
                     "collective-permute", "all-to-all")
# matches the op APPLICATION (`all-gather(...)` / `all-gather-start(`),
# never `-done(` continuations or instruction-NAME references (a name
# like %all-gather.1 is followed by `.1`/`)` — no open paren)
_COLLECTIVE_RE = re.compile(
    r"\b(" + "|".join(_COLLECTIVE_KINDS) + r")(?:-start)?\(")


def hlo_named_scopes(hlo_text: str) -> Set[str]:
    """All `/`-path components of op_name metadata in compiled HLO,
    with autodiff transform markers (jvp(...)/transpose(...)) peeled."""
    comps: Set[str] = set()
    for name in _OP_NAME_RE.findall(hlo_text):
        for part in re.split(r"[/()]", name):
            if part:
                comps.add(part)
    return comps


def missing_hlo_scopes(hlo_text: str, scopes: Iterable[str]) -> List[str]:
    """Scopes NOT present as a path component of any op_name. ONE
    matcher for every caller (test_telemetry's wrapper and the
    compiled-contract pins): a scope counts when it is a full
    `/ ( )`-delimited component, so autodiff transform markers —
    `jvp(embed)/...`, `transpose(jvp(mlp))/...` — still match."""
    comps = hlo_named_scopes(hlo_text)
    return [s for s in scopes if s not in comps]


def assert_hlo_scopes(hlo_text: str, scopes: Iterable[str]) -> None:
    missing = missing_hlo_scopes(hlo_text, scopes)
    assert not missing, \
        f"named scopes missing from compiled HLO metadata: {missing}"


def hlo_collective_census(hlo_text: str) -> Dict[str, int]:
    """{collective_kind: count} over compiled HLO text. Async pairs
    (all-gather-start/-done) count ONCE (the -start); the census is the
    pod-bill observable — a GSPMD regression that materializes a
    V-sharded embed all-gather moves a number here."""
    counts = {k: 0 for k in _COLLECTIVE_KINDS}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        counts[m.group(1)] += 1
    return counts


def hlo_donated_inputs(hlo_text: str) -> int:
    """Number of input->output alias entries in the compiled module
    header (donation verification: a donating step whose aliasing
    silently vanished doubles its peak HBM). The entry shape
    `{out_idx}: (param, {param_idx}[, may-alias])` only occurs in the
    HloModule header's input_output_alias block, so a global count is
    the block's count."""
    return len(_ALIAS_RE.findall(hlo_text))
