"""ctypes binding + lazy build for the native safetensors engine.

Same scheme as native/fast_bpe.py: libfast_safetensors.so is compiled from
fast_safetensors.cpp on first use with the system g++ (plain C ABI, no
pybind11) and cached next to the source; any failure degrades to None and
io/safetensors_io.py keeps its pure-Python path, which is the behavioral
reference. The native reader mmaps the file and hands back zero-copy numpy
windows into the blob; the writer streams tensors straight to disk without
concatenating the blob in memory.

Set MFT_NO_NATIVE_ST=1 to force the Python path (used by parity tests).
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from mobilefinetuner_tpu.native.build import load_native_library

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fast_safetensors.cpp")
_LIB = os.path.join(_HERE, "libfast_safetensors.so")


def _configure(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.st_open.restype = c.c_void_p
    lib.st_open.argtypes = [c.c_char_p]
    lib.st_error.restype = c.c_char_p
    lib.st_error.argtypes = [c.c_void_p]
    lib.st_count.restype = c.c_int32
    lib.st_count.argtypes = [c.c_void_p]
    # *_n functions return raw byte pointers + explicit length
    # (NOT c_char_p: names/metadata may contain NUL bytes)
    lib.st_key_n.restype = c.c_void_p
    lib.st_key_n.argtypes = [c.c_void_p, c.c_int32,
                             c.POINTER(c.c_int32)]
    lib.st_info_at.restype = c.c_int32
    lib.st_info_at.argtypes = [
        c.c_void_p, c.c_int32, c.c_char_p,
        c.POINTER(c.c_int32), c.POINTER(c.c_int64),
        c.POINTER(c.c_uint64), c.POINTER(c.c_uint64)]
    lib.st_blob.restype = c.POINTER(c.c_uint8)
    lib.st_blob.argtypes = [c.c_void_p]
    lib.st_meta_count.restype = c.c_int32
    lib.st_meta_count.argtypes = [c.c_void_p]
    lib.st_meta_key_n.restype = c.c_void_p
    lib.st_meta_key_n.argtypes = [c.c_void_p, c.c_int32,
                                  c.POINTER(c.c_int32)]
    lib.st_meta_val_n.restype = c.c_void_p
    lib.st_meta_val_n.argtypes = [c.c_void_p, c.c_int32,
                                  c.POINTER(c.c_int32)]
    lib.st_close.argtypes = [c.c_void_p]
    lib.stw_create.restype = c.c_void_p
    lib.stw_create.argtypes = [c.c_char_p]
    lib.stw_error.restype = c.c_char_p
    lib.stw_error.argtypes = [c.c_void_p]
    lib.stw_meta.argtypes = [c.c_void_p, c.c_char_p, c.c_int32,
                             c.c_char_p, c.c_int32]
    lib.stw_declare.restype = c.c_int32
    lib.stw_declare.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int32, c.c_char_p,
        c.POINTER(c.c_int64), c.c_int32, c.c_uint64]
    lib.stw_data.restype = c.c_int32
    lib.stw_data.argtypes = [c.c_void_p, c.c_void_p, c.c_uint64]
    lib.stw_finish.restype = c.c_int32
    lib.stw_finish.argtypes = [c.c_void_p]
    lib.stw_destroy.argtypes = [c.c_void_p]


def load_library() -> Optional[ctypes.CDLL]:
    return load_native_library(_SRC, _LIB, "MFT_NO_NATIVE_ST", _configure)


class _MmapView(np.ndarray):
    """ndarray subclass that pins the owning NativeReader alive: raw()
    views point into the reader's mmap, so a view outliving a GC'd reader
    would dangle — the `_owner` reference makes the mmap live at least as
    long as the view. An EXPLICIT close() still invalidates outstanding
    views (documented contract below)."""
    _owner = None


class NativeReader:
    """Parsed header + mmap'd blob. raw(name) returns a ZERO-COPY numpy
    byte window into the mmap — valid until an explicit close(); views
    keep the reader (and its mmap) alive across garbage collection."""

    def __init__(self, path: str):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native safetensors library unavailable")
        self._lib = lib
        self._h = lib.st_open(path.encode())
        if not self._h:
            raise MemoryError("st_open returned null")
        err = lib.st_error(self._h)
        if err:
            msg = err.decode()
            lib.st_close(self._h)
            self._h = None
            if msg == "cannot open file":
                # same exception type as the Python backend's open()
                raise FileNotFoundError(f"{path}: {msg}")
            raise ValueError(f"{path}: {msg}")
        self.entries: Dict[str, dict] = {}
        dt = ctypes.create_string_buffer(8)
        ndim = ctypes.c_int32()
        shape = (ctypes.c_int64 * 8)()
        begin = ctypes.c_uint64()
        end = ctypes.c_uint64()
        slen = ctypes.c_int32()

        def s(ptr):  # exact-length string (names may contain NUL bytes)
            return ctypes.string_at(ptr, slen.value).decode()

        for i in range(lib.st_count(self._h)):
            name = s(lib.st_key_n(self._h, i, ctypes.byref(slen)))
            rc = lib.st_info_at(self._h, i, dt, ctypes.byref(ndim),
                                shape, ctypes.byref(begin), ctypes.byref(end))
            if rc != 0:
                raise ValueError(f"{path}: bad entry {name!r} (rc={rc})")
            self.entries[name] = {
                "dtype": dt.value.decode(),
                "shape": list(shape[:ndim.value]),
                "data_offsets": [begin.value, end.value]}
        self.metadata: Dict[str, str] = {}
        for i in range(lib.st_meta_count(self._h)):
            k = s(lib.st_meta_key_n(self._h, i, ctypes.byref(slen)))
            self.metadata[k] = s(
                lib.st_meta_val_n(self._h, i, ctypes.byref(slen)))

    def raw(self, name: str) -> np.ndarray:
        """uint8 view of the tensor's bytes, zero-copy from the mmap."""
        if not self._h:
            raise ValueError("reader is closed")
        begin, end = self.entries[name]["data_offsets"]
        base = self._lib.st_blob(self._h)
        if not base:
            raise ValueError("no blob mapped")
        buf = (ctypes.c_uint8 * (end - begin)).from_address(
            ctypes.addressof(base.contents) + begin)
        arr = np.frombuffer(buf, dtype=np.uint8).view(_MmapView)
        arr._owner = self  # pin the mmap for the view's lifetime
        arr.flags.writeable = False
        return arr

    def close(self):
        if getattr(self, "_h", None):
            self._lib.st_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def native_write(path: str, tensors: List[tuple],
                 metadata: Optional[Dict[str, str]] = None) -> None:
    """Write a safetensors file natively, streamed: tensors is a list of
    (name, tag, shape, nbytes, payload) in final order, where payload is
    either the raw bytes or a zero-arg callable returning them. The header
    is written from the declarations alone (two-pass stw_declare/stw_data
    protocol), and callable payloads are materialized ONE AT A TIME during
    the data pass — peak host memory is a single encoded tensor, not the
    whole checkpoint. Raises on any writer error."""
    lib = load_library()
    if lib is None:
        raise RuntimeError("native safetensors library unavailable")
    h = lib.stw_create(path.encode())
    try:
        if metadata:
            for k, v in metadata.items():
                kb, vb = str(k).encode(), str(v).encode()
                lib.stw_meta(h, kb, len(kb), vb, len(vb))
        for name, tag, shape, nbytes, _payload in tensors:
            sh = (ctypes.c_int64 * max(len(shape), 1))(*shape)
            nb = name.encode()
            if lib.stw_declare(h, nb, len(nb), tag.encode(), sh,
                               len(shape), nbytes) != 0:
                raise IOError(lib.stw_error(h).decode())
        for name, tag, shape, nbytes, payload in tensors:
            raw = payload() if callable(payload) else payload
            if len(raw) != nbytes:
                raise IOError(f"{name}: payload {len(raw)} bytes != "
                              f"declared {nbytes}")
            if lib.stw_data(h, raw, len(raw)) != 0:
                raise IOError(lib.stw_error(h).decode())
        if lib.stw_finish(h) != 0:
            err = lib.stw_error(h)
            raise IOError(err.decode() if err else "writer finish failed")
    finally:
        lib.stw_destroy(h)
