// Native BPE merge engine — the hot loop of GPT-2 byte-level BPE.
//
// C++ counterpart of the reference's tokenizer core
// (reference: operators/finetune_ops/core/tokenizer_bpe.cpp — greedy
// lowest-rank pair merging over the byte->unicode-mapped word), built as a
// shared library and driven from Python via ctypes
// (mobilefinetuner_tpu/native/fast_bpe.py). The Python tokenizer keeps the
// unicode-category pre-tokenization regex and the per-word cache; this
// engine replaces only the merge loop + vocab lookup, and must match the
// Python reference implementation token-for-token (tests/test_native_bpe.py
// asserts parity; the Python side is itself HF-oracle-tested).
//
// Merge semantics mirror the canonical algorithm exactly, including the
// left-to-right `word.index(a, i)` rebuild pass.
//
// Build: g++ -O2 -shared -fPIC fast_bpe.cpp -o libfast_bpe.so
// (done automatically on first use by fast_bpe.py).

#include <climits>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

struct PairHash {
    size_t operator()(const std::pair<std::string, std::string>& p) const {
        std::hash<std::string> h;
        return h(p.first) * 1000003u ^ h(p.second);
    }
};

struct Engine {
    std::unordered_map<std::pair<std::string, std::string>, int, PairHash>
        ranks;
    std::unordered_map<std::string, int32_t> vocab;
    int next_rank = 0;
};

std::vector<std::string> split_utf8(const char* s) {
    std::vector<std::string> out;
    size_t n = std::strlen(s);
    size_t i = 0;
    while (i < n) {
        unsigned char c = static_cast<unsigned char>(s[i]);
        size_t len = 1;
        if ((c & 0x80u) == 0x00u) len = 1;
        else if ((c & 0xE0u) == 0xC0u) len = 2;
        else if ((c & 0xF0u) == 0xE0u) len = 3;
        else if ((c & 0xF8u) == 0xF0u) len = 4;
        // clamp: a truncated/invalid lead byte must not read past the
        // terminator (this symbol is extern-C callable by anyone)
        if (len > n - i) len = n - i;
        out.emplace_back(s + i, len);
        i += len;
    }
    return out;
}

}  // namespace

extern "C" {

void* bpe_create() { return new Engine(); }

void bpe_destroy(void* h) { delete static_cast<Engine*>(h); }

// rank = insertion order (call in merges.txt order). Assignment (not
// emplace) + an always-incrementing counter mirror Python's
// {pair: i for i, pair in enumerate(merges)}: a duplicate pair keeps its
// LAST index and still consumes a rank slot.
void bpe_add_merge(void* h, const char* a, const char* b) {
    Engine* e = static_cast<Engine*>(h);
    e->ranks[std::make_pair(std::string(a), std::string(b))] =
        e->next_rank++;
}

void bpe_add_token(void* h, const char* token, int32_t id) {
    static_cast<Engine*>(h)->vocab[token] = id;
}

// Batch load: one FFI call instead of one per entry (~100k round-trips
// for the real GPT-2 tables). merges_blob = "a b\na b\n..." in rank
// order; vocab_blob = "tok\ntok\n..." parallel to ids. Token strings are
// byte->unicode mapped, so they never contain ' ', '\n', or NUL.
void bpe_load(void* h, const char* merges_blob, const char* vocab_blob,
              const int32_t* ids, int32_t n_vocab) {
    Engine* e = static_cast<Engine*>(h);
    const char* p = merges_blob;
    while (*p) {
        const char* sp = p;
        while (*sp && *sp != ' ') ++sp;
        const char* nl = sp;
        while (*nl && *nl != '\n') ++nl;
        if (*sp == ' ') {
            e->ranks[std::make_pair(std::string(p, sp - p),
                                    std::string(sp + 1, nl - sp - 1))] =
                e->next_rank++;
        }
        p = (*nl == '\n') ? nl + 1 : nl;
    }
    p = vocab_blob;
    for (int32_t i = 0; i < n_vocab && *p; ++i) {
        const char* nl = p;
        while (*nl && *nl != '\n') ++nl;
        e->vocab[std::string(p, nl - p)] = ids[i];
        p = (*nl == '\n') ? nl + 1 : nl;
    }
}

// Encode one byte->unicode-mapped word (utf-8). Writes ids into out;
// returns the count, or -1 if cap is too small (caller retries bigger).
int32_t bpe_encode_word(void* h, const char* word, int32_t* out,
                        int32_t cap, int32_t unk_id) {
    Engine* e = static_cast<Engine*>(h);
    std::vector<std::string> parts = split_utf8(word);
    if (parts.empty()) return 0;

    while (parts.size() > 1) {
        int best_rank = INT_MAX;
        std::pair<std::string, std::string> best;
        for (size_t i = 0; i + 1 < parts.size(); ++i) {
            auto it = e->ranks.find({parts[i], parts[i + 1]});
            if (it != e->ranks.end() && it->second < best_rank) {
                best_rank = it->second;
                best = it->first;
            }
        }
        if (best_rank == INT_MAX) break;

        // rebuild pass, python `word.index(a, i)` semantics
        std::vector<std::string> nw;
        nw.reserve(parts.size());
        size_t i = 0;
        while (i < parts.size()) {
            size_t j = i;
            while (j < parts.size() && parts[j] != best.first) ++j;
            for (size_t k = i; k < j; ++k) nw.push_back(parts[k]);
            if (j >= parts.size()) break;
            if (j + 1 < parts.size() && parts[j + 1] == best.second) {
                nw.push_back(best.first + best.second);
                i = j + 2;
            } else {
                nw.push_back(parts[j]);
                i = j + 1;
            }
        }
        parts.swap(nw);
    }

    int32_t n = 0;
    for (const auto& p : parts) {
        if (n >= cap) return -1;
        auto it = e->vocab.find(p);
        out[n++] = (it == e->vocab.end()) ? unk_id : it->second;
    }
    return n;
}

}  // extern "C"
