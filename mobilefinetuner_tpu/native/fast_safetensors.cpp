// Native safetensors engine: mmap'd reader + buffered writer, plain C ABI.
//
// The runtime-native counterpart of io/safetensors_io.py (which stays as the
// behavioral reference and automatic fallback). Mirrors the CAPABILITY of the
// reference's C++ loader (reference: operators/finetune_ops/graph/
// safetensors_loader.{h,cpp}: 8-byte LE header length + JSON header + raw
// blob, F32/F16 focus) but is an independent design: a tagged-union JSON
// parser instead of field scraping, mmap + zero-copy tensor windows instead
// of per-tensor reads, and BF16 as a first-class tag (TPU parameter dtype).
//
// Build: g++ -O2 -shared -fPIC fast_safetensors.cpp -o libfast_safetensors.so
// (driven lazily by native/fast_safetensors.py, same scheme as fast_bpe).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------- JSON ----
// Minimal recursive-descent JSON parser. Safetensors headers are flat
// machine-written JSON, but we parse the full grammar (incl. \u escapes)
// so any spec-conformant producer round-trips.

struct JValue;
// insertion-ordered object: safetensors key order is file order and must
// round-trip (Python's json preserves it; a sorted map would not)
using JObject = std::vector<std::pair<std::string, JValue>>;
struct JValue {
  enum Kind { kNull, kBool, kNum, kStr, kArr, kObj } kind = kNull;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<JValue> arr;
  std::shared_ptr<JObject> obj;  // shared_ptr: JObject is incomplete here
};

const JValue* jfind(const JObject& o, const char* key) {
  for (const auto& kv : o)
    if (kv.first == key) return &kv.second;
  return nullptr;
}

struct JParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }
  bool lit(const char* s) {
    size_t n = strlen(s);
    if (size_t(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  bool parse_hex4(uint32_t* out) {
    if (end - p < 4) return false;
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) {
      char c = p[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= uint32_t(c - '0');
      else if (c >= 'a' && c <= 'f') v |= uint32_t(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= uint32_t(c - 'A' + 10);
      else return false;
    }
    p += 4;
    *out = v;
    return true;
  }

  static void append_utf8(std::string* s, uint32_t cp) {
    if (cp < 0x80) {
      s->push_back(char(cp));
    } else if (cp < 0x800) {
      s->push_back(char(0xC0 | (cp >> 6)));
      s->push_back(char(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      s->push_back(char(0xE0 | (cp >> 12)));
      s->push_back(char(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(char(0x80 | (cp & 0x3F)));
    } else {
      s->push_back(char(0xF0 | (cp >> 18)));
      s->push_back(char(0x80 | ((cp >> 12) & 0x3F)));
      s->push_back(char(0x80 | ((cp >> 6) & 0x3F)));
      s->push_back(char(0x80 | (cp & 0x3F)));
    }
  }

  bool parse_string(std::string* out) {
    if (p >= end || *p != '"') return false;
    p++;
    out->clear();
    while (p < end && *p != '"') {
      char c = *p++;
      if (c != '\\') {
        out->push_back(c);
        continue;
      }
      if (p >= end) return false;
      char e = *p++;
      switch (e) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          uint32_t cp;
          if (!parse_hex4(&cp)) return false;
          if (cp >= 0xD800 && cp <= 0xDBFF) {  // surrogate pair
            if (end - p < 6 || p[0] != '\\' || p[1] != 'u') return false;
            p += 2;
            uint32_t lo;
            if (!parse_hex4(&lo) || lo < 0xDC00 || lo > 0xDFFF) return false;
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          }
          append_utf8(out, cp);
          break;
        }
        default: return false;
      }
    }
    if (p >= end) return false;
    p++;  // closing quote
    return true;
  }

  bool parse_value(JValue* v) {
    skip_ws();
    if (p >= end) return false;
    switch (*p) {
      case '{': {
        p++;
        v->kind = JValue::kObj;
        v->obj = std::make_shared<JObject>();
        skip_ws();
        if (p < end && *p == '}') { p++; return true; }
        while (true) {
          skip_ws();
          std::string key;
          if (!parse_string(&key)) return false;
          skip_ws();
          if (p >= end || *p++ != ':') return false;
          JValue child;
          if (!parse_value(&child)) return false;
          v->obj->emplace_back(std::move(key), std::move(child));
          skip_ws();
          if (p < end && *p == ',') { p++; continue; }
          if (p < end && *p == '}') { p++; return true; }
          return false;
        }
      }
      case '[': {
        p++;
        v->kind = JValue::kArr;
        skip_ws();
        if (p < end && *p == ']') { p++; return true; }
        while (true) {
          JValue child;
          if (!parse_value(&child)) return false;
          v->arr.push_back(std::move(child));
          skip_ws();
          if (p < end && *p == ',') { p++; continue; }
          if (p < end && *p == ']') { p++; return true; }
          return false;
        }
      }
      case '"':
        v->kind = JValue::kStr;
        return parse_string(&v->str);
      case 't': v->kind = JValue::kBool; v->b = true; return lit("true");
      case 'f': v->kind = JValue::kBool; v->b = false; return lit("false");
      case 'n': v->kind = JValue::kNull; return lit("null");
      default: {
        char* q = nullptr;
        v->kind = JValue::kNum;
        v->num = strtod(p, &q);
        if (q == p || q > end) return false;
        p = q;
        return true;
      }
    }
  }
};

// ------------------------------------------------------------- reader -----

struct TensorEntry {
  std::string name;
  std::string dtype;                // safetensors tag: "F32", "BF16", ...
  std::vector<int64_t> shape;
  uint64_t begin = 0, end = 0;      // offsets within the blob
};

struct Reader {
  int fd = -1;
  uint8_t* map = nullptr;
  size_t file_size = 0;
  uint64_t blob_off = 0;            // 8 + header_len
  std::vector<TensorEntry> tensors;
  std::vector<std::pair<std::string, std::string>> metadata;
  std::string error;
};

Reader* reader_fail(Reader* r, const char* msg) {
  r->error = msg;
  return r;  // caller inspects st_error()
}

}  // namespace

extern "C" {

// Opens the file; returns a handle even on failure (query st_error, then
// st_close). A null return means allocation failed.
void* st_open(const char* path) {
  Reader* r = new Reader();
  r->fd = ::open(path, O_RDONLY);
  if (r->fd < 0) return reader_fail(r, "cannot open file");
  struct stat st;
  if (fstat(r->fd, &st) != 0 || st.st_size < 8)
    return reader_fail(r, "file too small for safetensors header");
  r->file_size = size_t(st.st_size);
  r->map = static_cast<uint8_t*>(
      mmap(nullptr, r->file_size, PROT_READ, MAP_PRIVATE, r->fd, 0));
  if (r->map == MAP_FAILED) {
    r->map = nullptr;
    return reader_fail(r, "mmap failed");
  }
  uint64_t header_len;
  memcpy(&header_len, r->map, 8);   // little-endian file, LE hosts only
  if (header_len > r->file_size - 8)
    return reader_fail(r, "header length exceeds file size");
  r->blob_off = 8 + header_len;

  std::string hdr(reinterpret_cast<const char*>(r->map + 8), header_len);
  JParser jp(hdr);
  JValue root;
  if (!jp.parse_value(&root) || root.kind != JValue::kObj)
    return reader_fail(r, "header is not a JSON object");

  uint64_t blob_size = r->file_size - r->blob_off;
  for (auto& kv : *root.obj) {
    if (kv.first == "__metadata__") {
      if (kv.second.kind == JValue::kObj)
        for (auto& m : *kv.second.obj)
          if (m.second.kind == JValue::kStr)
            r->metadata.emplace_back(m.first, m.second.str);
      continue;
    }
    if (kv.second.kind != JValue::kObj)
      return reader_fail(r, "tensor entry is not an object");
    const JObject& e = *kv.second.obj;
    TensorEntry t;
    t.name = kv.first;
    const JValue* dt = jfind(e, "dtype");
    const JValue* sh = jfind(e, "shape");
    const JValue* off = jfind(e, "data_offsets");
    if (!dt || dt->kind != JValue::kStr ||
        !sh || sh->kind != JValue::kArr ||
        !off || off->kind != JValue::kArr || off->arr.size() != 2)
      return reader_fail(r, "malformed tensor entry");
    t.dtype = dt->str;
    for (auto& d : sh->arr) {
      if (d.kind != JValue::kNum) return reader_fail(r, "non-numeric dim");
      t.shape.push_back(int64_t(d.num));
    }
    t.begin = uint64_t(off->arr[0].num);
    t.end = uint64_t(off->arr[1].num);
    if (t.begin > t.end || t.end > blob_size)
      return reader_fail(r, "tensor offsets out of range");
    r->tensors.push_back(std::move(t));
  }
  return r;
}

const char* st_error(void* h) {
  Reader* r = static_cast<Reader*>(h);
  return r->error.empty() ? nullptr : r->error.c_str();
}

int32_t st_count(void* h) {
  return int32_t(static_cast<Reader*>(h)->tensors.size());
}

// Length-aware: JSON strings may legally contain NUL bytes, which a
// NUL-terminated char* cannot represent. Returns the byte pointer and
// writes the exact length.
const char* st_key_n(void* h, int32_t i, int32_t* len) {
  Reader* r = static_cast<Reader*>(h);
  if (i < 0 || size_t(i) >= r->tensors.size()) return nullptr;
  *len = int32_t(r->tensors[i].name.size());
  return r->tensors[i].name.data();
}

// Fills dtype tag (cap>=8 incl. NUL), ndim, shape (cap 8) and the blob
// window [begin, end) for tensor index i (the Python wrapper iterates by
// index, so names never cross the FFI as NUL-terminated strings).
// Returns 0, or -1 for a bad index, -2 for ndim > 8.
int32_t st_info_at(void* h, int32_t i, char* dtype_out, int32_t* ndim,
                   int64_t* shape_out, uint64_t* begin, uint64_t* end) {
  Reader* r = static_cast<Reader*>(h);
  if (i < 0 || size_t(i) >= r->tensors.size()) return -1;
  const TensorEntry& t = r->tensors[i];
  if (t.shape.size() > 8) return -2;  // caller's shape buffer is 8 slots
  snprintf(dtype_out, 8, "%s", t.dtype.c_str());
  *ndim = int32_t(t.shape.size());
  for (size_t i = 0; i < t.shape.size(); i++)
    shape_out[i] = t.shape[i];
  *begin = t.begin;
  *end = t.end;
  return 0;
}

// Base pointer of the mmap'd blob; tensor bytes live at base+begin.
const uint8_t* st_blob(void* h) {
  Reader* r = static_cast<Reader*>(h);
  return r->map ? r->map + r->blob_off : nullptr;
}

int32_t st_meta_count(void* h) {
  return int32_t(static_cast<Reader*>(h)->metadata.size());
}

const char* st_meta_key_n(void* h, int32_t i, int32_t* len) {
  Reader* r = static_cast<Reader*>(h);
  if (i < 0 || size_t(i) >= r->metadata.size()) return nullptr;
  *len = int32_t(r->metadata[i].first.size());
  return r->metadata[i].first.data();
}

const char* st_meta_val_n(void* h, int32_t i, int32_t* len) {
  Reader* r = static_cast<Reader*>(h);
  if (i < 0 || size_t(i) >= r->metadata.size()) return nullptr;
  *len = int32_t(r->metadata[i].second.size());
  return r->metadata[i].second.data();
}

void st_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  if (r->map) munmap(r->map, r->file_size);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

// ------------------------------------------------------------- writer -----
// Streamed two-pass writer: callers declare every tensor (name/tag/shape/
// size) up front, then the header is emitted once and tensor bytes are
// appended in declaration order — no in-memory concatenation of the blob.

namespace {

struct PendingTensor {
  std::string name, dtype;
  std::vector<int64_t> shape;
  uint64_t nbytes = 0;
};

struct Writer {
  std::string path;
  FILE* f = nullptr;
  std::vector<PendingTensor> pending;
  std::vector<std::pair<std::string, std::string>> metadata;
  bool header_written = false;
  size_t write_cursor = 0;   // next tensor expected by st_write_data
  std::string error;
};

void json_escape(const std::string& s, std::string* out) {
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\b': *out += "\\b"; break;
      case '\f': *out += "\\f"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(char(c));
        }
    }
  }
}

}  // namespace

void* stw_create(const char* path) {
  Writer* w = new Writer();
  w->path = path;
  return w;
}

const char* stw_error(void* h) {
  Writer* w = static_cast<Writer*>(h);
  return w->error.empty() ? nullptr : w->error.c_str();
}

// Length-aware (names/values may contain NUL bytes, which JSON escapes).
void stw_meta(void* h, const char* key, int32_t key_len, const char* val,
              int32_t val_len) {
  static_cast<Writer*>(h)->metadata.emplace_back(
      std::string(key, size_t(key_len)), std::string(val, size_t(val_len)));
}

int32_t stw_declare(void* h, const char* name, int32_t name_len,
                    const char* dtype, const int64_t* shape, int32_t ndim,
                    uint64_t nbytes) {
  Writer* w = static_cast<Writer*>(h);
  if (w->header_written) {
    w->error = "declare after header written";
    return -1;
  }
  PendingTensor t;
  t.name.assign(name, size_t(name_len));
  t.dtype = dtype;
  t.shape.assign(shape, shape + ndim);
  t.nbytes = nbytes;
  w->pending.push_back(std::move(t));
  return 0;
}

// Emits the 8-byte length + JSON header (8-byte space-padded, matching the
// HF writer convention). Idempotent.
static int32_t stw_write_header(void* h) {
  Writer* w = static_cast<Writer*>(h);
  if (!w->header_written) {
    std::string hdr = "{";
    bool first = true;
    if (!w->metadata.empty()) {
      hdr += "\"__metadata__\":{";
      bool mf = true;
      for (auto& kv : w->metadata) {
        if (!mf) hdr += ",";
        mf = false;
        hdr += "\"";
        json_escape(kv.first, &hdr);
        hdr += "\":\"";
        json_escape(kv.second, &hdr);
        hdr += "\"";
      }
      hdr += "}";
      first = false;
    }
    uint64_t off = 0;
    for (auto& t : w->pending) {
      if (!first) hdr += ",";
      first = false;
      hdr += "\"";
      json_escape(t.name, &hdr);
      hdr += "\":{\"dtype\":\"" + t.dtype + "\",\"shape\":[";
      for (size_t i = 0; i < t.shape.size(); i++) {
        if (i) hdr += ",";
        hdr += std::to_string(t.shape[i]);
      }
      hdr += "],\"data_offsets\":[" + std::to_string(off) + "," +
             std::to_string(off + t.nbytes) + "]}";
      off += t.nbytes;
    }
    hdr += "}";
    while (hdr.size() % 8) hdr += " ";
    w->f = fopen(w->path.c_str(), "wb");
    if (!w->f) {
      w->error = "cannot open output file";
      return -1;
    }
    uint64_t hlen = hdr.size();
    if (fwrite(&hlen, 8, 1, w->f) != 1 ||
        fwrite(hdr.data(), 1, hdr.size(), w->f) != hdr.size()) {
      w->error = "header write failed";
      return -1;
    }
    w->header_written = true;
  }
  return 0;
}

// Writes one tensor's bytes; tensors MUST arrive in declaration order. The
// first call emits the header.
int32_t stw_data(void* h, const uint8_t* data, uint64_t nbytes) {
  Writer* w = static_cast<Writer*>(h);
  if (stw_write_header(h) != 0) return -1;
  if (w->write_cursor >= w->pending.size() ||
      nbytes != w->pending[w->write_cursor].nbytes) {
    w->error = "tensor data out of declared order/size";
    return -1;
  }
  if (nbytes && fwrite(data, 1, nbytes, w->f) != nbytes) {
    w->error = "data write failed";
    return -1;
  }
  w->write_cursor++;
  return 0;
}

int32_t stw_finish(void* h) {
  Writer* w = static_cast<Writer*>(h);
  int32_t rc = 0;
  if (!w->header_written) stw_write_header(h);  // zero-tensor file
  if (!w->error.empty() || w->write_cursor != w->pending.size()) {
    if (w->error.empty()) w->error = "missing tensor data at finish";
    rc = -1;
  }
  if (w->f && fclose(w->f) != 0 && rc == 0) {
    w->error = "close failed";
    rc = -1;
  }
  w->f = nullptr;
  return rc;
}

void stw_destroy(void* h) {
  Writer* w = static_cast<Writer*>(h);
  if (w->f) fclose(w->f);
  delete w;
}

}  // extern "C"
