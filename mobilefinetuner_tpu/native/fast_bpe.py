"""ctypes binding + lazy build for the native BPE merge engine.

The shared library (libfast_bpe.so) is compiled from fast_bpe.cpp on first
use with the system g++ (no pybind11 dependency; plain C ABI + ctypes) and
cached next to the source; a stale .so (older than the .cpp) is rebuilt.
Any failure — no compiler, unwritable dir, load error — degrades silently
to None and the tokenizer keeps its pure-Python path
(data/tokenizer_bpe.py), which is the behavioral reference.

Set MFT_NO_NATIVE_BPE=1 to force the Python path (used by parity tests).
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Tuple

from mobilefinetuner_tpu.native.build import load_native_library

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fast_bpe.cpp")
_LIB = os.path.join(_HERE, "libfast_bpe.so")


def _configure(lib: ctypes.CDLL) -> None:
    lib.bpe_create.restype = ctypes.c_void_p
    lib.bpe_destroy.argtypes = [ctypes.c_void_p]
    lib.bpe_add_merge.argtypes = [ctypes.c_void_p,
                                  ctypes.c_char_p,
                                  ctypes.c_char_p]
    lib.bpe_add_token.argtypes = [ctypes.c_void_p,
                                  ctypes.c_char_p,
                                  ctypes.c_int32]
    lib.bpe_load.argtypes = [ctypes.c_void_p,
                             ctypes.c_char_p,
                             ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_int32),
                             ctypes.c_int32]
    lib.bpe_encode_word.restype = ctypes.c_int32
    lib.bpe_encode_word.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ctypes.c_int32]


def load_library() -> Optional[ctypes.CDLL]:
    return load_native_library(_SRC, _LIB, "MFT_NO_NATIVE_BPE", _configure)


class NativeBPE:
    """One engine instance per tokenizer: merges + vocab loaded once."""

    def __init__(self, merges: List[Tuple[str, str]], vocab):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native BPE library unavailable")
        self._lib = lib
        self._h = lib.bpe_create()
        # one FFI call per table (not per entry — real GPT-2 has ~50k of
        # each); mapped tokens never contain ' ', '\n', or NUL
        merges_blob = "".join(f"{a} {b}\n" for a, b in merges)
        tokens = list(vocab)
        vocab_blob = "".join(t + "\n" for t in tokens)
        ids = (ctypes.c_int32 * len(tokens))(
            *(int(vocab[t]) for t in tokens))
        lib.bpe_load(self._h, merges_blob.encode("utf-8"),
                     vocab_blob.encode("utf-8"), ids, len(tokens))

    def encode_word(self, mapped_word: str, unk_id: int) -> List[int]:
        """ids for one byte->unicode-mapped word (matches the Python
        _bpe + vocab-lookup result exactly)."""
        raw = mapped_word.encode("utf-8")
        cap = max(len(mapped_word), 1)
        while True:
            buf = (ctypes.c_int32 * cap)()
            n = self._lib.bpe_encode_word(self._h, raw, buf, cap, unk_id)
            if n >= 0:
                return list(buf[:n])
            cap *= 2

    def __del__(self):
        try:
            self._lib.bpe_destroy(self._h)
        except Exception:
            pass
