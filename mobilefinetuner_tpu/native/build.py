"""Shared lazy-build + load machinery for the native C++ engines.

One copy of the scheme all three engines use (fast_bpe, fast_gemma_bpe,
fast_safetensors): compile the .cpp next to it with the system g++ on
first use (plain C ABI — no pybind11), cache the .so beside the source,
rebuild when the source is newer, and degrade to None on ANY failure so
the pure-Python reference path takes over. An env kill switch per engine
forces the Python path (parity tests use it).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Optional

_lock = threading.Lock()
_caches: dict = {}  # lib_path -> [lib_or_None]


def _build(src: str, lib_path: str) -> bool:
    # unique temp output: concurrent builders (pytest-xdist, two CLIs)
    # must not interleave writes into one file and install a corrupt .so
    tmp = f"{lib_path}.tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-shared", "-fPIC", src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib_path)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_native_library(src: str, lib_path: str, disable_env: str,
                        configure: Callable[[ctypes.CDLL], None]
                        ) -> Optional[ctypes.CDLL]:
    """Load (building if stale) the shared library; `configure` sets the
    ctypes restype/argtypes. Returns None when disabled or unavailable."""
    if os.environ.get(disable_env) == "1":
        return None
    with _lock:
        cache = _caches.setdefault(lib_path, [])
        if cache:
            return cache[0]
        lib = None
        try:
            stale = (not os.path.exists(lib_path)
                     or os.path.getmtime(lib_path) < os.path.getmtime(src))
            if not stale or _build(src, lib_path):
                lib = ctypes.CDLL(lib_path)
                configure(lib)
        except Exception:
            lib = None
        cache.append(lib)
        return lib
