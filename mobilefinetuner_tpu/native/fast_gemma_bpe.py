"""ctypes binding + lazy build for the native Gemma BPE merge engine.

Same scheme as native/fast_bpe.py: libfast_gemma_bpe.so is compiled from
fast_gemma_bpe.cpp on first use (plain C ABI, no pybind11) and cached next
to the source; any failure degrades to None and data/tokenizer_gemma.py
keeps its pure-Python heap BPE, which is the behavioral reference. Tables
cross the FFI once, as length-prefixed blobs (Gemma vocab pieces may
contain newlines/spaces, so no delimiter format is safe).

Set MFT_NO_NATIVE_GEMMA_BPE=1 to force the Python path (parity tests).
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Dict, List, Optional, Tuple

from mobilefinetuner_tpu.native.build import load_native_library

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fast_gemma_bpe.cpp")
_LIB = os.path.join(_HERE, "libfast_gemma_bpe.so")


def _configure(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.gbpe_create.restype = c.c_void_p
    lib.gbpe_destroy.argtypes = [c.c_void_p]
    lib.gbpe_load.restype = c.c_int32
    lib.gbpe_load.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int64, c.c_char_p,
        c.c_int64, c.c_int32, c.c_int32]
    lib.gbpe_encode.restype = c.c_int32
    lib.gbpe_encode.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int64,
        c.POINTER(c.c_int32), c.c_int32]


def load_library() -> Optional[ctypes.CDLL]:
    return load_native_library(_SRC, _LIB, "MFT_NO_NATIVE_GEMMA_BPE",
                               _configure)


def _rec(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


class NativeGemmaBPE:
    """One engine per tokenizer: ranks + vocab + byte-fallback table
    loaded once; encode_chunk(normalized_text) -> ids, exactly matching
    tokenizer_gemma._encode_chunk's BPE+lookup stage."""

    def __init__(self, merges: List[Tuple[str, str]], vocab: Dict[str, int],
                 unk_id: Optional[int], byte_fallback: bool):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native Gemma BPE library unavailable")
        self._lib = lib
        self._h = lib.gbpe_create()
        mb = b"".join(_rec(a.encode()) + _rec(b.encode())
                      for a, b in merges)
        vb = b"".join(_rec(t.encode()) + struct.pack("<i", i)
                      for t, i in vocab.items())
        rc = lib.gbpe_load(self._h, mb, len(mb), vb, len(vb),
                           -1 if unk_id is None else int(unk_id),
                           int(bool(byte_fallback)))
        if rc != 0:
            raise RuntimeError(f"gbpe_load failed (rc={rc})")

    def encode_chunk(self, text: str) -> List[int]:
        raw = text.encode("utf-8")
        # every emitted id consumes >= 1 source byte (vocab pieces and
        # byte-fallback alike), so len(raw) always suffices; the retry
        # loop is belt-and-braces
        cap = max(len(raw), 1)
        while True:
            buf = (ctypes.c_int32 * cap)()
            n = self._lib.gbpe_encode(self._h, raw, len(raw), buf, cap)
            if n == -1:
                cap *= 2
                continue
            if n == -3:
                raise KeyError(
                    "byte_fallback token missing from vocab "
                    "(matches the Python reference's KeyError)")
            return list(buf[:n])

    def __del__(self):
        try:
            self._lib.gbpe_destroy(self._h)
        except Exception:
            pass
