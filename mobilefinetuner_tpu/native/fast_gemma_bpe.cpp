// Native SentencePiece-style BPE merge engine for the Gemma tokenizer.
//
// The runtime-native counterpart of data/tokenizer_gemma.py::_bpe_heap +
// vocab/byte-fallback lookup (which stays as the behavioral reference and
// automatic fallback). The reference's C++ Gemma tokenizer is slow enough
// that it ships an offline pretokenizer (reference: core/tokenizer_gemma.cpp,
// scripts/pretokenize_wikitext2_gemma.py; SURVEY.md §2.4) — this engine is
// the opposite design: a heap over adjacent-pair ranks on a doubly-linked
// symbol list (O(n log n) per chunk), loaded once, called per normalized
// chunk.
//
// Exact-parity contract with the Python implementation, including heap
// tie-breaking: entries order by (rank, left-position, left-sym, right-sym)
// — bytewise string comparison equals Python's code-point comparison for
// valid UTF-8.
//
// All strings cross the FFI length-prefixed (tokens may contain '\n', ' ',
// or any byte): records are [u32 len][bytes] (+ [i32 id] in the vocab blob).
//
// Build: g++ -O2 -shared -fPIC fast_gemma_bpe.cpp -o libfast_gemma_bpe.so
// (driven lazily by native/fast_gemma_bpe.py).

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PairHash {
  size_t operator()(const std::pair<std::string, std::string>& p) const {
    std::hash<std::string> h;
    return h(p.first) * 1000003u ^ h(p.second);
  }
};

struct Engine {
  std::unordered_map<std::pair<std::string, std::string>, int32_t, PairHash>
      ranks;
  std::unordered_map<std::string, int32_t> vocab;
  int32_t byte_ids[256];       // <0xXX> token ids; -1 = absent
  int32_t unk_id = -1;         // -1 = no unk (unmatched pieces dropped)
  bool byte_fallback = false;
};

struct HeapEntry {
  int32_t rank;
  int32_t pos;
  std::string a, b;
  // min-heap via std::priority_queue (max-heap + inverted comparison);
  // full tuple ordering mirrors Python's heapq tuples (r, i, a, b)
  bool operator<(const HeapEntry& o) const {
    if (rank != o.rank) return rank > o.rank;
    if (pos != o.pos) return pos > o.pos;
    if (a != o.a) return a > o.a;
    return b > o.b;
  }
};

std::vector<std::string> split_utf8(const char* s, size_t n) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < n) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    size_t len = 1;
    if ((c & 0x80u) == 0x00u) len = 1;
    else if ((c & 0xE0u) == 0xC0u) len = 2;
    else if ((c & 0xF0u) == 0xE0u) len = 3;
    else if ((c & 0xF8u) == 0xF0u) len = 4;
    if (len > n - i) len = n - i;  // truncated tail: clamp, don't overrun
    out.emplace_back(s + i, len);
    i += len;
  }
  return out;
}

bool read_rec(const uint8_t*& p, const uint8_t* end, std::string* out) {
  if (end - p < 4) return false;
  uint32_t len;
  memcpy(&len, p, 4);
  p += 4;
  if (uint32_t(end - p) < len) return false;
  out->assign(reinterpret_cast<const char*>(p), len);
  p += len;
  return true;
}

}  // namespace

extern "C" {

void* gbpe_create() {
  Engine* e = new Engine();
  for (int i = 0; i < 256; i++) e->byte_ids[i] = -1;
  return e;
}

void gbpe_destroy(void* h) { delete static_cast<Engine*>(h); }

// merges_blob: [u32 la][a][u32 lb][b]... in rank order.
// vocab_blob:  [u32 lt][token][i32 id]...
// Duplicate merge pairs keep their LAST rank index while still consuming a
// slot (Python dict-comprehension semantics).
int32_t gbpe_load(void* h, const uint8_t* merges_blob, int64_t merges_len,
                  const uint8_t* vocab_blob, int64_t vocab_len,
                  int32_t unk_id, int32_t byte_fallback) {
  Engine* e = static_cast<Engine*>(h);
  const uint8_t* p = merges_blob;
  const uint8_t* end = merges_blob + merges_len;
  int32_t rank = 0;
  std::string a, b;
  while (p < end) {
    if (!read_rec(p, end, &a) || !read_rec(p, end, &b)) return -1;
    e->ranks[std::make_pair(a, b)] = rank++;
  }
  p = vocab_blob;
  end = vocab_blob + vocab_len;
  std::string tok;
  while (p < end) {
    if (!read_rec(p, end, &tok)) return -1;
    if (end - p < 4) return -1;
    int32_t id;
    memcpy(&id, p, 4);
    p += 4;
    e->vocab[tok] = id;
    // register byte-fallback tokens: exactly "<0xXX>" with UPPERCASE hex
    // (the Python reference looks up f"<0x{byte:02X}>" only — lowercase
    // spellings must stay unregistered so both paths KeyError alike)
    if (tok.size() == 6 && tok.compare(0, 3, "<0x") == 0 && tok[5] == '>') {
      auto hex = [](char c) -> int {
        if (c >= '0' && c <= '9') return c - '0';
        if (c >= 'A' && c <= 'F') return c - 'A' + 10;
        return -1;
      };
      int hi = hex(tok[3]), lo = hex(tok[4]);
      if (hi >= 0 && lo >= 0) e->byte_ids[hi * 16 + lo] = id;
    }
  }
  e->unk_id = unk_id;
  e->byte_fallback = byte_fallback != 0;
  return 0;
}

// Heap-BPE one normalized chunk (utf-8, length-delimited) into token ids.
// Returns the id count, -1 when cap is too small (caller retries), or -3
// when byte_fallback needs a <0xXX> token the vocab lacks (the Python
// reference raises KeyError there; the caller mirrors that).
int32_t gbpe_encode(void* h, const char* text, int64_t text_len,
                    int32_t* out, int32_t cap) {
  Engine* e = static_cast<Engine*>(h);
  std::vector<std::string> sym = split_utf8(text, size_t(text_len));
  const int n = int(sym.size());
  if (n == 0) return 0;

  std::vector<int> nxt(n), prv(n);
  std::vector<char> alive(n, 1);
  for (int i = 0; i < n; i++) {
    nxt[i] = (i + 1 < n) ? i + 1 : -1;
    prv[i] = i - 1;
  }
  if (n > 1) {
    std::priority_queue<HeapEntry> heap;
    for (int i = 0; i + 1 < n; i++) {
      auto it = e->ranks.find({sym[i], sym[i + 1]});
      if (it != e->ranks.end())
        heap.push({it->second, i, sym[i], sym[i + 1]});
    }
    while (!heap.empty()) {
      HeapEntry t = heap.top();
      heap.pop();
      int i = t.pos;
      if (!alive[i] || sym[i] != t.a) continue;
      int j = nxt[i];
      if (j == -1 || !alive[j] || sym[j] != t.b) continue;
      sym[i] = t.a + t.b;
      alive[j] = 0;
      nxt[i] = nxt[j];
      if (nxt[j] != -1) prv[nxt[j]] = i;
      int p2 = prv[i];
      if (p2 != -1 && alive[p2]) {
        auto it = e->ranks.find({sym[p2], sym[i]});
        if (it != e->ranks.end())
          heap.push({it->second, p2, sym[p2], sym[i]});
      }
      int q = nxt[i];
      if (q != -1 && alive[q]) {
        auto it = e->ranks.find({sym[i], sym[q]});
        if (it != e->ranks.end())
          heap.push({it->second, i, sym[i], sym[q]});
      }
    }
  }

  // Emit ids by walking the surviving linked list: vocab hit, else
  // byte-fallback (<0xXX> per utf-8 byte), else unk, else drop —
  // tokenizer_gemma.py _encode_chunk order exactly.
  int32_t count = 0;
  for (int i = 0; i != -1; i = nxt[i]) {
    if (!alive[i]) continue;
    const std::string& piece = sym[i];
    auto it = e->vocab.find(piece);
    if (it != e->vocab.end()) {
      if (count >= cap) return -1;
      out[count++] = it->second;
    } else if (e->byte_fallback) {
      for (unsigned char c : piece) {
        if (count >= cap) return -1;
        int32_t bid = e->byte_ids[c];
        if (bid < 0) return -3;  // Python raises KeyError here
        out[count++] = bid;
      }
    } else if (e->unk_id >= 0) {
      if (count >= cap) return -1;
      out[count++] = e->unk_id;
    }
  }
  return count;
}

}  // extern "C"
