"""Rotary position embeddings (RoPE), HF-Gemma/LLaMA rotate-half convention.

Reference: operators/finetune_ops/core/ops.cpp:2151 `apply_rope` and the
Gemma dual-theta selection (graph/gemma_model.cpp:579-625): global layers use
theta=1e6, sliding-window layers theta=1e4 (SURVEY.md §2.5).

Computed in fp32 for accuracy, cast back to the input dtype.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(positions: jnp.ndarray, head_dim: int,
                 theta: float) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables [..., S, head_dim] for integer positions [..., S]
    ([S] shared across the batch, or [B, S] per-row, e.g. mask-derived
    positions for left-padded batches).

    HF convention: inv_freq over even dims, each frequency repeated across
    the two halves (rotate_half pairing dim i with dim i + head_dim/2).
    """
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray,
               sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, H, S, D]; cos/sin: [S, D] or [B, S, D] → same shape/dtype.

    expand_dims inserts the head axis: [S,D]->[1,S,D] (broadcast over B,H),
    [B,S,D]->[B,1,S,D] (broadcast over H)."""
    orig = x.dtype
    xf = x.astype(jnp.float32)
    c = jnp.expand_dims(cos, -3)
    s = jnp.expand_dims(sin, -3)
    out = xf * c + _rotate_half(xf) * s
    return out.astype(orig)
