"""Rotary position embeddings (RoPE), HF-Gemma/LLaMA rotate-half convention.

Reference: operators/finetune_ops/core/ops.cpp:2151 `apply_rope` and the
Gemma dual-theta selection (graph/gemma_model.cpp:579-625): global layers use
theta=1e6, sliding-window layers theta=1e4 (SURVEY.md §2.5).

Computed in fp32 for accuracy, cast back to the input dtype.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(positions: jnp.ndarray, head_dim: int,
                 theta: float) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables [S, head_dim] for integer positions [S].

    HF convention: inv_freq over even dims, each frequency repeated across
    the two halves (rotate_half pairing dim i with dim i + head_dim/2).
    """
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    freqs = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray,
               sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, H, S, D]; cos/sin: [S, D] → same shape, same dtype as x."""
    orig = x.dtype
    xf = x.astype(jnp.float32)
    out = xf * cos[None, None, :, :] + _rotate_half(xf) * sin[None, None, :, :]
    return out.astype(orig)
