"""Pallas TPU flash attention: fused, O(S) HBM, differentiable, block-sparse.

Replaces the reference's forward-only streaming-softmax attention
(reference: core/memory_efficient_attention.{h,cpp} — FlashAttention-style
two-pass row streaming, scalar loops, registers NO backward node, SURVEY.md
§2.12.1) with a TPU-native block kernel that IS differentiable: a
custom_vjp whose forward saves only (out, logsumexp) and whose backward
recomputes probabilities blockwise — activation memory stays O(B·H·S·D),
never O(B·H·S²), in HBM.

Design (sized for the fine-tuning regime S ≤ ~2k, D ≤ 256):
  - forward grid (B, Hq, S/BQ), all dims parallel; each program owns one
    [BQ, D] query block and loops over key blocks with ONLINE softmax,
    visiting only blocks the mask can reach: causal skips the strictly-
    upper-triangular blocks (~2× FLOPs) and a sliding window w visits
    O(w/BK + 1) blocks per query block, so Gemma's local layers cost
    O(S·w), not O(S²);
  - K/V for the (batch, kv-head) live whole in VMEM (S·D·4B ≤ ~2 MB at
    S=2048 D=256); the k-loop slices them in VMEM — block skipping saves
    MXU FLOPs, which dominate at these shapes;
  - GQA by BlockSpec index mapping: q-head h reads kv-head h // group —
    K/V are never materialized per-q-head (the reference materializes via
    repeat_kv_heads, core/ops.cpp:2072);
  - causal + sliding-window + key-padding masks built from broadcasted
    iotas inside the kernel;
  - backward has TWO implementations behind a selector (resolve_bwd_impl;
    'auto' picks merged whenever its VMEM accounting fits, the split pair
    remains the parity oracle and the large-shape fallback):
      merged (default): ONE kernel, grid (B, Hq, S/BK) with only the
             innermost key-block dim sequential. Each program owns one
             [BK, D] key block, loops over the q-blocks that can see it
             (causal: qi ≥ ki·BK/BQ; window: qi·BQ < ki·BK+BK+w), and
             computes dK/dV *and* the dQ contributions from ONE
             recomputation of (s, p, dp, ds) — the split pair recomputes
             those twice (7 tile matmuls vs 5, ~29% of backward MXU
             work), reads K/V/dO/LSE from HBM twice, and costs twice the
             kernel launches (the S=1024 GPT-2s step runs 24 backward
             launches split, 12 merged). dQ accumulates in an f32 VMEM
             scratch slab across the sequential key-block steps and is
             written once on the last step; GQA emits per-q-head dK/dV
             partials that XLA group-sums (free when Hq == Hkv).
      split (oracle):
       dQ:   grid (B, Hq, S/BQ), ALL dims parallel, same skipping k-loop
             as the forward;
       dK/dV: grid (B, S/BK, Hq) with only the innermost head dim
             sequential (fully parallel when Hq == Hkv); accumulates the
             G q-heads of a kv-head over consecutive innermost steps.
    Δ = rowsum(dO ∘ O) is precomputed in XLA (one fused elementwise pass).

For shapes the kernel doesn't support (S not a multiple of the block, tiny
D, explicit attn_mask matrices), ops/attention.py's XLA path is the
fallback — same numerics, same mask semantics (it is the oracle the kernel
is tested against).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mobilefinetuner_tpu.ops.pallas_util import (interpret_mode as
                                                 _interpret,
                                                 tpu_call_params)

NEG_INF = -1e30


def _valid_blocks(S: int, block_q: int,
                  block_k: int) -> Optional[tuple]:
    """Largest hardware-valid (block_q, block_k) <= requested, or None
    (caller falls back to XLA).

    Mosaic constraints on v5e (verified by compiling): query-side dynamic
    slices hit the SUBLANE dim (8-aligned offsets -> block_q % 8 == 0);
    the key-padding row [1, S] is sliced on the LANE dim (128-aligned ->
    block_k % 128 == 0). A single whole-S block is exempt: the kernels
    index it statically (no dynamic slice), which keeps short/odd S
    (e.g. 64, 192) on the kernel exactly as the pre-block-loop version did.
    Whole-S fallback is capped at 1024 so [BQ, S] scores stay VMEM-sized.
    """
    bq = bk = None
    for b in (block_q, 512, 384, 256, 128):
        if b <= block_q and b <= S and S % b == 0 and b % 8 == 0:
            bq = b
            break
    if bq is None and S <= 1024 and S % 8 == 0:
        bq = S
    for b in (block_k, 512, 384, 256, 128):
        if b <= block_k and b <= S and S % b == 0 and b % 128 == 0:
            bk = b
            break
    if bk is None and S <= 1024 and S % 8 == 0:
        bk = S  # single block: static path, no alignment constraint
    if bq is None or bk is None:
        return None
    return bq, bk


def _kv_block_bounds(row0, block_q, block_k, n_kv_blocks, causal, window):
    """[lo, hi) k-block range reachable from query rows
    [row0, row0+block_q): causal caps hi at the diagonal block; a sliding
    window lifts lo to the first block any row can still see."""
    if causal:
        hi = jnp.minimum(n_kv_blocks,
                         (row0 + block_q - 1) // block_k + 1)
    else:
        hi = n_kv_blocks
    if window is not None:
        lo = jnp.maximum(0, (row0 - window + 1) // block_k)
    else:
        lo = 0
    return lo, hi


def _block_mask(row0, col0, block_q, block_k, causal, window, pad_blk):
    """[BQ, BK] bool attend-mask for one (q-block, k-block) tile."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + row0
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + col0
    mask = pad_blk > 0                    # key padding [1|BQ, BK]
    if causal:
        mask &= cols <= rows
    if window is not None:
        mask &= cols > rows - window
    return mask


def _keep_mask(seed, b, h, row0, col0, block_q, block_k, p_drop):
    """[BQ, BK] bool keep-mask for attention dropout on one tile.

    Counter-based: a lowbias32-style integer mix of (seed, batch, head,
    global row, global col) — each (b, h, i, j) cell's bit is a pure
    function of its coordinates, so the forward and BOTH backward kernels
    regenerate identical masks regardless of their different tile
    iteration orders, with no [S, S] mask ever materialized. Plain 32-bit
    jnp arithmetic (wrapping int32 mul/xor/shift), so hardware and
    interpret mode agree bit-for-bit and the tests' numpy reimplementation
    is exact (tests/test_flash_attention.py)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + row0
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + col0
    x = (seed ^ (b * jnp.int32(-1640531527))        # 0x9E3779B9
         ^ (h * jnp.int32(-2048144789)))            # 0x85EBCA6B
    z = (x + rows * jnp.int32(-1028477387)          # 0xC2B2AE35
         + cols * jnp.int32(668265263))             # 0x27D4EB2F
    z = z ^ ((z >> 16) & 0xFFFF)
    z = z * jnp.int32(0x7FEB352D)
    z = z ^ ((z >> 15) & 0x1FFFF)
    z = z * jnp.int32(-2073254261)                  # 0x846CA68B
    z = z ^ ((z >> 16) & 0xFFFF)
    # uniform u24 from the high bits; keep iff below the keep threshold
    u24 = (z >> 8) & 0xFFFFFF
    thresh = jnp.int32(round((1.0 - p_drop) * (1 << 24)))
    return u24 < thresh


# --------------------------------- forward ----------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, pad_ref, seed_ref, o_ref, lse_ref, *,
                scale, block_q, block_k, causal, window, S, p_drop):
    b = pl.program_id(0)
    h = pl.program_id(1)
    qi = pl.program_id(2)
    row0 = qi * block_q
    q = q_ref[0, 0].astype(jnp.float32)           # [BQ, D]
    D = q.shape[-1]

    def step(col0, k, v, pad, carry):
        m, l, acc = carry
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _block_mask(row0, col0, block_q, k.shape[0], causal, window,
                           pad)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)        # [BQ, BK]
        alpha = jnp.exp(m - m_new)
        # HF probs-dropout semantics: the softmax DENOMINATOR sums the
        # undropped probs (l), only the value accumulation sees the
        # dropped+rescaled weights — out = dropout(softmax(s)) @ v.
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if p_drop > 0.0:
            keep = _keep_mask(seed_ref[0], b, h, row0, col0, block_q,
                              k.shape[0], p_drop)
            pv = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - p_drop))
        else:
            pv = p
        acc = acc * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    init = (jnp.full((block_q, 1), NEG_INF, jnp.float32),
            jnp.zeros((block_q, 1), jnp.float32),
            jnp.zeros((block_q, D), jnp.float32))
    if block_k == S:
        # single whole-S block: static indexing (no alignment constraint)
        m, l, acc = step(0, k_ref[0, 0].astype(jnp.float32),
                         v_ref[0, 0].astype(jnp.float32), pad_ref[0],
                         init)
    else:
        nK = S // block_k
        lo, hi = _kv_block_bounds(row0, block_q, block_k, nK, causal,
                                  window)

        def body(ki, carry):
            col0 = ki * block_k
            return step(
                col0,
                k_ref[0, 0, pl.ds(col0, block_k), :].astype(jnp.float32),
                v_ref[0, 0, pl.ds(col0, block_k), :].astype(jnp.float32),
                pad_ref[0, :, pl.ds(col0, block_k)], carry)
        m, l, acc = jax.lax.fori_loop(lo, hi, body, init)
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)            # [BQ, 1]


def _fwd(q, k, v, padding_mask, seed, *, scale, causal, window, block_q,
         block_k, p_drop=0.0):
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    grid = (B, Hq, S // block_q)
    pad3 = padding_mask.reshape(B, 1, S)
    kernel = functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                               block_k=block_k, causal=causal,
                               window=window, S=S, p_drop=p_drop)
    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, S, 1), jnp.float32),
        ],
        **tpu_call_params("parallel", "parallel", "parallel"),
    )
    # semantic trace annotation: the kernel shows up as attention/flash_fwd
    # in profiler traces and HLO metadata (DESIGN.md §13)
    with jax.named_scope("attention"), jax.named_scope("flash_fwd"):
        out, lse = call(q, k, v, pad3, seed)
    return out, lse


# --------------------------------- backward ---------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, pad_ref, seed_ref, lse_ref, delta_ref,
               do_ref, dq_ref, *, scale, block_q, block_k, causal, window,
               S, p_drop):
    b = pl.program_id(0)
    h = pl.program_id(1)
    qi = pl.program_id(2)
    row0 = qi * block_q
    q = q_ref[0, 0].astype(jnp.float32)            # [BQ, D]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                            # [BQ, 1]
    delta = delta_ref[0, 0]                        # [BQ, 1]
    D = q.shape[-1]

    def step(col0, k, v, pad, dq):
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _block_mask(row0, col0, block_q, k.shape[0], causal, window,
                           pad)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)          # [BQ, BK]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if p_drop > 0.0:
            # regenerate the forward's keep mask for this tile; with
            # probs-dropout, Δ = rowsum(dO∘O) already equals
            # Σ_k p_ik·(m/keep·dp)_ik, so ds = p∘(dp∘m/keep − Δ)
            keep = _keep_mask(seed_ref[0], b, h, row0, col0, block_q,
                              k.shape[0], p_drop)
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - p_drop))
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros((block_q, D), jnp.float32)
    if block_k == S:
        dq = step(0, k_ref[0, 0].astype(jnp.float32),
                  v_ref[0, 0].astype(jnp.float32), pad_ref[0], dq0)
    else:
        nK = S // block_k
        lo, hi = _kv_block_bounds(row0, block_q, block_k, nK, causal,
                                  window)

        def body(ki, dq):
            col0 = ki * block_k
            return step(
                col0,
                k_ref[0, 0, pl.ds(col0, block_k), :].astype(jnp.float32),
                v_ref[0, 0, pl.ds(col0, block_k), :].astype(jnp.float32),
                pad_ref[0, :, pl.ds(col0, block_k)], dq)
        dq = jax.lax.fori_loop(lo, hi, body, dq0)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _q_block_bounds(col0, block_q, block_k, nQ, causal, window):
    """[qlo, qhi) q-block range that can see key block at col0 — the
    transpose of _kv_block_bounds, shared by the split dK/dV kernel and
    the merged one-pass kernel so the visibility arithmetic cannot
    drift between them."""
    if causal:
        qlo = col0 // block_q
    else:
        qlo = 0
    if window is not None:
        qhi = jnp.minimum(nQ, (col0 + block_k + window - 2) // block_q + 1)
    else:
        qhi = nQ
    return qlo, qhi


def _bwd_tile(qb, dob, lseb, deltab, k, v, pad, seed, b, h, row0, col0,
              block_q, block_k, scale, causal, window, p_drop):
    """One (q-block, k-block) backward tile — the single recomputation of
    (s, p, dp, ds) both backward implementations share. Returns
    (pv, ds): pv is the dropped+rescaled probs feeding dV (pvᵀ·dO), ds
    feeds dK (dsᵀ·q) and dQ (ds·k)."""
    s = jax.lax.dot_general(qb, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    mask = _block_mask(row0, col0, block_q, block_k, causal, window, pad)
    p = jnp.where(mask, jnp.exp(s - lseb), 0.0)             # [BQ, BK]
    dp = jax.lax.dot_general(dob, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if p_drop > 0.0:
        keep = _keep_mask(seed, b, h, row0, col0, block_q, block_k,
                          p_drop)
        inv_keep = 1.0 / (1.0 - p_drop)
        pv = jnp.where(keep, p, 0.0) * inv_keep      # dropped+rescaled p̃
        dp = jnp.where(keep, dp, 0.0) * inv_keep
    else:
        pv = p
    ds = p * (dp - deltab) * scale                          # [BQ, BK]
    return pv, ds


def _dkv_kernel(q_ref, k_ref, v_ref, pad_ref, seed_ref, lse_ref, delta_ref,
                do_ref, dk_ref, dv_ref, *, scale, block_q, block_k, causal,
                window, S, G, p_drop):
    b = pl.program_id(0)
    ki = pl.program_id(1)
    h = pl.program_id(2)
    col0 = ki * block_k
    k = k_ref[0, 0].astype(jnp.float32)            # [BK, D]
    v = v_ref[0, 0].astype(jnp.float32)
    pad = pad_ref[0]                               # [1, BK]
    D = k.shape[-1]
    # q-blocks that can see this key block (transpose of the fwd bounds)
    qlo, qhi = _q_block_bounds(col0, block_q, block_k, S // block_q,
                               causal, window)

    def body(qi, carry):
        dk, dv = carry
        row0 = qi * block_q
        qb = q_ref[0, 0, pl.ds(row0, block_q), :].astype(jnp.float32)
        dob = do_ref[0, 0, pl.ds(row0, block_q), :].astype(jnp.float32)
        pv, ds = _bwd_tile(
            qb, dob, lse_ref[0, 0, pl.ds(row0, block_q), :],
            delta_ref[0, 0, pl.ds(row0, block_q), :], k, v, pad,
            seed_ref[0], b, h, row0, col0, block_q, block_k, scale,
            causal, window, p_drop)
        dv = dv + jax.lax.dot_general(
            pv, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BK, D]
        dk = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(qlo, qhi, body, (z, z))

    if G == 1:
        dk_ref[0, 0] = dk
        dv_ref[0, 0] = dv
    else:
        # accumulate the G q-heads of this kv-head across the CONSECUTIVE
        # innermost (sequential) head steps; first head of a group inits
        @pl.when(h % G == 0)
        def _init():
            dk_ref[0, 0] = dk
            dv_ref[0, 0] = dv

        @pl.when(h % G != 0)
        def _acc():
            dk_ref[0, 0] += dk
            dv_ref[0, 0] += dv


def _dkvq_kernel(q_ref, k_ref, v_ref, pad_ref, seed_ref, lse_ref, delta_ref,
                 do_ref, dq_ref, dk_ref, dv_ref, dq_acc, *, scale, block_q,
                 block_k, causal, window, S, p_drop):
    """Merged one-pass backward: dK, dV AND the dQ contributions of one
    [BK, D] key block from a single recomputation of (s, p, dp, ds).

    Grid (B, Hq, S/BK), key-block dim innermost and SEQUENTIAL: the dQ
    slab for (b, h) accumulates in the f32 VMEM scratch `dq_acc` across
    the consecutive key-block steps (zeroed at ki == 0, flushed to the
    output in q.dtype at the last step), so dQ is read-modify-written in
    VMEM only — never round-tripped through HBM per key block. dK/dV are
    emitted per Q-HEAD ([B, Hq, S, D] partials); the GQA group-sum
    happens in XLA outside (one fused reduction, a no-op when G == 1)
    because the per-kv-head blocks would otherwise be revisited
    non-consecutively across the outer head dim, which Pallas TPU output
    residency does not allow."""
    b = pl.program_id(0)
    h = pl.program_id(1)
    ki = pl.program_id(2)
    col0 = ki * block_k
    k = k_ref[0, 0].astype(jnp.float32)            # [BK, D]
    v = v_ref[0, 0].astype(jnp.float32)
    pad = pad_ref[0]                               # [1, BK]
    D = k.shape[-1]
    nK = S // block_k

    @pl.when(ki == 0)
    def _zero():
        dq_acc[...] = jnp.zeros(dq_acc.shape, dq_acc.dtype)

    qlo, qhi = _q_block_bounds(col0, block_q, block_k, S // block_q,
                               causal, window)

    def body(qi, carry):
        dk, dv = carry
        row0 = qi * block_q
        qb = q_ref[0, 0, pl.ds(row0, block_q), :].astype(jnp.float32)
        dob = do_ref[0, 0, pl.ds(row0, block_q), :].astype(jnp.float32)
        pv, ds = _bwd_tile(
            qb, dob, lse_ref[0, 0, pl.ds(row0, block_q), :],
            delta_ref[0, 0, pl.ds(row0, block_q), :], k, v, pad,
            seed_ref[0], b, h, row0, col0, block_q, block_k, scale,
            causal, window, p_drop)
        dv = dv + jax.lax.dot_general(
            pv, dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [BK, D]
        dk = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # the pass the split pair duplicates: dQ rows reuse THIS tile's ds
        dq_acc[pl.ds(row0, block_q), :] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((block_k, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(qlo, qhi, body, (z, z))
    dk_ref[0, 0] = dk
    dv_ref[0, 0] = dv

    @pl.when(ki == nK - 1)
    def _flush():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


_BWD_VMEM_BUDGET = 12 * 2 ** 20


def merged_bwd_fits(S: int, D: int, block_k: int, itemsize: int) -> bool:
    """VMEM accounting for one merged-backward program: whole-S q/dO
    slabs + the f32 dQ accumulator + the dQ output block + lse/Δ rows
    resident for the whole (b, h) sweep, plus double-buffered K/V input
    and dK/dV output blocks."""
    need = (2 * S * D * itemsize          # q + dO slabs
            + S * D * 4                   # dq f32 scratch accumulator
            + S * D * itemsize            # dq output block
            + 2 * S * 4                   # lse + delta rows
            + 2 * 2 * block_k * D * itemsize   # K/V blocks, double-buffered
            + 2 * 2 * block_k * D * 4)    # dk/dv out blocks, double-buffered
    return need <= _BWD_VMEM_BUDGET


def resolve_bwd_impl(S: int, D: int, block_k: int, itemsize: int) -> str:
    """The backward 'auto' rule, mirroring ops/attention.resolve_impl:
    the merged one-pass kernel whenever its VMEM accounting fits — it
    does for every bf16 training shape the forward dispatches today
    (S ≤ 2048 at D ≤ 256) and for f32 up to S=2048 at D=64; f32
    Gemma-shaped S=2048 D=256 slabs exceed the budget and take the split
    FlashAttention-2 pair. Kept as ONE function so the vjp and the tests
    force paths through the same gate."""
    return "merged" if merged_bwd_fits(S, D, block_k, itemsize) else "split"


def _bwd_merged(scale, causal, window, block_q, block_k, p_drop, q, k, v,
                pad3, seed, lse, delta, do):
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    kernel = functools.partial(
        _dkvq_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, S=S, p_drop=p_drop)
    call = pl.pallas_call(
        kernel,
        grid=(B, Hq, S // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i: (b, h // G, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i: (b, h // G, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, S, 1), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, 1), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, S, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hq, S, D), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((S, D), jnp.float32)],
        **tpu_call_params("parallel", "parallel", "arbitrary"),
    )
    with jax.named_scope("attention"), jax.named_scope("flash_bwd_merged"):
        dq, dk_p, dv_p = call(q, k, v, pad3, seed, lse, delta, do)
    if G > 1:
        dk = dk_p.reshape(B, Hkv, G, S, D).sum(axis=2)
        dv = dv_p.reshape(B, Hkv, G, S, D).sum(axis=2)
    else:
        dk, dv = dk_p, dv_p
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), None, None


def _bwd(scale, causal, window, block_q, block_k, res, g, dlse=None,
         p_drop=0.0, bwd_impl="auto"):
    q, k, v, padding_mask, seed, out, lse = res
    do = g
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    pad3 = padding_mask.reshape(B, 1, S)
    # Δ = rowsum(dO ∘ O): one fused XLA pass, shared by every kernel.
    # A joint (out, lse) cotangent (the ring-attention partials) folds in
    # exactly here: ∂lse/∂s_ij = p_ij, so ds_ij = p_ij(dO·v_j − Δ_i +
    # dlse_i) — i.e. Δ ← Δ − dlse, with dv untouched (∂lse/∂v = 0). The
    # kernels themselves are unchanged, so the folding works identically
    # for the merged and split backward implementations.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)

    if bwd_impl == "auto":
        bwd_impl = resolve_bwd_impl(S, D, block_k, q.dtype.itemsize)
    if bwd_impl == "merged":
        return _bwd_merged(scale, causal, window, block_q, block_k,
                           p_drop, q, k, v, pad3, seed, lse, delta, do)
    assert bwd_impl == "split", bwd_impl

    dq_kernel = functools.partial(
        _dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, S=S, p_drop=p_drop)
    dq_call = pl.pallas_call(
        dq_kernel,
        grid=(B, Hq, S // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i: (b, h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, Hq, S, D), q.dtype),
        **tpu_call_params("parallel", "parallel", "parallel"),
    )
    with jax.named_scope("attention"), jax.named_scope("flash_bwd_dq"):
        dq = dq_call(q, k, v, pad3, seed, lse, delta, do)

    dkv_kernel = functools.partial(
        _dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, window=window, S=S, G=G, p_drop=p_drop)
    # head dim innermost: a kv-head's G q-heads hit the same dk/dv block on
    # consecutive steps (safe accumulate); fully parallel when G == 1
    dkv_call = pl.pallas_call(
        dkv_kernel,
        grid=(B, S // block_k, Hq),
        in_specs=[
            pl.BlockSpec((1, 1, S, D), lambda b, i, h: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, i, h: (b, h // G, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, i, h: (b, h // G, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k), lambda b, i, h: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, S, 1), lambda b, i, h: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, 1), lambda b, i, h: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, i, h: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, i, h: (b, h // G, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, i, h: (b, h // G, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, S, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, S, D), jnp.float32),
        ],
        **tpu_call_params("parallel", "parallel",
                          "parallel" if G == 1 else "arbitrary"),
    )
    with jax.named_scope("attention"), jax.named_scope("flash_bwd_dkv"):
        dk, dv = dkv_call(q, k, v, pad3, seed, lse, delta, do)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), None, None


# ------------------------------- public API ---------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, padding_mask, seed, scale, causal, window, block_q,
           block_k, p_drop, bwd_impl):
    out, _ = _fwd(q, k, v, padding_mask, seed, scale=scale, causal=causal,
                  window=window, block_q=block_q, block_k=block_k,
                  p_drop=p_drop)
    return out


def _flash_fwd(q, k, v, padding_mask, seed, scale, causal, window, block_q,
               block_k, p_drop, bwd_impl):
    out, lse = _fwd(q, k, v, padding_mask, seed, scale=scale, causal=causal,
                    window=window, block_q=block_q, block_k=block_k,
                    p_drop=p_drop)
    return out, (q, k, v, padding_mask, seed, out, lse)


def _flash_bwd(scale, causal, window, block_q, block_k, p_drop, bwd_impl,
               res, g):
    return _bwd(scale, causal, window, block_q, block_k, res, g,
                p_drop=p_drop, bwd_impl=bwd_impl)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_lse(q, k, v, padding_mask, seed, scale, causal, window, block_q,
               block_k, bwd_impl):
    """(out, lse) with gradients through BOTH outputs — the online-softmax
    partial for ring attention's cross-device merge. No dropout: partials
    compose across devices, and dropout on a renormalized merge would
    change semantics — the ring path is eval/long-context training where
    attention dropout is off."""
    return _fwd(q, k, v, padding_mask, seed, scale=scale, causal=causal,
                window=window, block_q=block_q, block_k=block_k)


def _flash_lse_fwd(q, k, v, padding_mask, seed, scale, causal, window,
                   block_q, block_k, bwd_impl):
    out, lse = _fwd(q, k, v, padding_mask, seed, scale=scale, causal=causal,
                    window=window, block_q=block_q, block_k=block_k)
    return (out, lse), (q, k, v, padding_mask, seed, out, lse)


def _flash_lse_bwd(scale, causal, window, block_q, block_k, bwd_impl, res,
                   g):
    do, dlse = g
    return _bwd(scale, causal, window, block_q, block_k, res, do,
                dlse=dlse, bwd_impl=bwd_impl)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_partial_eligible(S: int, D: int) -> bool:
    """Can flash_attention_partial serve a [.., S, D] shard? (The ring
    dispatcher asks this OUTSIDE shard_map, where the decision must be
    static.)"""
    return D in (64, 128, 256) and _valid_blocks(S, 512, 512) is not None


def flash_attention_partial(q, k, v, padding_mask=None, *,
                            scale: Optional[float] = None,
                            is_causal: bool = True,
                            sliding_window: Optional[int] = None,
                            block_q: int = 512, block_k: int = 512,
                            bwd_impl: str = "auto"):
    """Partial-attention stats (out, lse) for online-softmax composition
    (parallel/ring_attention.py), or None when the shape is not
    kernel-eligible (caller falls back to its dense path).

    Unlike flash_attention, causal and sliding_window are INDEPENDENT
    here: a ring hop t attends its queries against a K/V chunk sitting
    t·S_chunk rows earlier, which is a non-causal band mask — expressed
    as is_causal=False with sliding_window = window − t·S_chunk (negative
    values shift the band above the local diagonal; the block-bounds and
    mask arithmetic handle them as-is). Differentiable w.r.t. q/k/v
    through BOTH out and lse (see _bwd's Δ−dlse folding)."""
    if bwd_impl not in ("auto", "merged", "split"):
        raise ValueError(f"bwd_impl must be 'auto', 'merged' or 'split', "
                         f"got {bwd_impl!r}")
    B, Hq, S, D = q.shape
    if D not in (64, 128, 256) or k.shape[2] != S:
        return None
    picked = _valid_blocks(S, block_q, block_k)
    if _interpret() and S % block_q == 0 and S % block_k == 0:
        picked = (block_q, block_k)
    if picked is None:
        return None
    block_q, block_k = picked
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if padding_mask is None:
        pad = jnp.ones((B, S), jnp.float32)
    else:
        pad = padding_mask.astype(jnp.float32)
    return _flash_lse(q, k, v, pad, jnp.zeros((1,), jnp.int32),
                      float(scale), bool(is_causal),
                      None if sliding_window is None
                      else int(sliding_window),
                      int(block_q), int(block_k), str(bwd_impl))


def flash_attention(q, k, v, *,
                    scale: Optional[float] = None,
                    is_causal: bool = True,
                    sliding_window: Optional[int] = None,
                    padding_mask: Optional[jnp.ndarray] = None,
                    attn_mask: Optional[jnp.ndarray] = None,
                    logits_dtype=jnp.float32,
                    attn_dropout: float = 0.0,
                    attn_dropout_rng: Optional[jnp.ndarray] = None,
                    block_q: int = 512,
                    block_k: int = 512,
                    bwd_impl: str = "auto") -> jnp.ndarray:
    """Drop-in for ops.attention.dot_product_attention (same signature).

    attn_mask (a precomputed [S, S] matrix) has no blockwise structure the
    kernel can exploit, so that case falls back to the XLA path — model code
    passes is_causal/sliding_window instead (gemma3 selects masks per layer
    by flags, not matrices, when using the flash impl).

    attn_dropout (train-mode probs dropout, HF semantics): generated
    INSIDE the kernels from a counter-based hash of (seed, b, h, row, col)
    (_keep_mask) — no [.., S, S] mask is ever materialized, and the
    backward kernels regenerate the identical mask from the same seed. The
    keep decisions come from a different (hash-based) generator than the
    XLA path's jax.random stream, so the two impls agree in DISTRIBUTION,
    not per-mask — exactly like the reference's RNG vs ours. Dropout=0 or
    rng=None compiles the dropout-free kernels (p_drop is static).

    bwd_impl selects the backward kernel implementation: 'auto' (the
    merged one-pass dK/dV+dQ kernel whenever resolve_bwd_impl's VMEM
    accounting admits it), 'merged', or 'split' (the FlashAttention-2
    two-kernel pair — the parity oracle and large-shape fallback).

    Default blocks are 512×512 (clamped to S): measured on TPU v5e,
    large blocks amortize the k-loop — every smaller block combination
    swept at S <= 512 (r4: 256x512 down to 64x128) only added
    per-program overhead. The kernel wins end-to-end from S >= 512 at
    D=64 (+20% on the GPT-2s train step) and from S >= 2048 at D=256;
    below that XLA's fused attention keeps the edge (thresholds in
    attention() 'auto' / resolve_impl).
    """
    from mobilefinetuner_tpu.ops.attention import dot_product_attention
    if bwd_impl not in ("auto", "merged", "split"):
        raise ValueError(f"bwd_impl must be 'auto', 'merged' or 'split', "
                         f"got {bwd_impl!r}")
    B, Hq, S, D = q.shape
    # sliding_window implies causal in the oracle's mask semantics
    # (attention.causal_mask is always causal when a window is given);
    # mirror that so kernel and fallback never diverge
    is_causal = is_causal or sliding_window is not None
    picked = _valid_blocks(S, block_q, block_k)
    if _interpret() and S % block_q == 0 and S % block_k == 0:
        # interpret mode has no Mosaic alignment constraints; honor the
        # requested blocks so tests can exercise the multi-block loop at
        # small S (the hardware path is still dispatched via _valid_blocks)
        picked = (block_q, block_k)
    if picked is not None:
        block_q, block_k = picked
    if (attn_mask is not None or picked is None
            or D not in (64, 128, 256)):
        return dot_product_attention(
            q, k, v, scale=scale, is_causal=is_causal,
            sliding_window=sliding_window, padding_mask=padding_mask,
            attn_mask=attn_mask, logits_dtype=logits_dtype,
            attn_dropout=attn_dropout,
            attn_dropout_rng=attn_dropout_rng)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if padding_mask is None:
        pad = jnp.ones((B, S), jnp.float32)
    else:
        pad = padding_mask.astype(jnp.float32)
    # graftlint: disable=sync-hazard(attn_dropout is a concrete Python config scalar at trace time, never a tracer)
    p_drop = float(attn_dropout) if attn_dropout_rng is not None else 0.0
    if p_drop > 0.0:
        seed = jax.lax.bitcast_convert_type(
            jax.random.bits(attn_dropout_rng, (1,), jnp.uint32), jnp.int32)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    return _flash(q, k, v, pad, seed, float(scale), bool(is_causal),
                  None if sliding_window is None else int(sliding_window),
                  int(block_q), int(block_k), p_drop, str(bwd_impl))
