"""Pallas TPU flash attention: fused, O(S) HBM, differentiable.

Replaces the reference's forward-only streaming-softmax attention
(reference: core/memory_efficient_attention.{h,cpp} — FlashAttention-style
two-pass row streaming, scalar loops, registers NO backward node, SURVEY.md
§2.12.1) with a TPU-native block kernel that IS differentiable: a
custom_vjp whose forward saves only (out, logsumexp) and whose backward
recomputes probabilities blockwise — activation memory stays O(B·H·S·D),
never O(B·H·S²), in HBM.

Design (sized for the fine-tuning regime S ≤ ~2k, D ≤ 256):
  - grid (B, Hq, S/BQ); each program computes one [BQ, D] query block;
  - K/V for the (batch, kv-head) live whole in VMEM (S·D·4B ≤ ~2 MB at
    S=2048 D=256), so scores are one [BQ, S] MXU matmul — no inner online-
    softmax loop; [BQ, S] fp32 stays in VMEM and never reaches HBM;
  - GQA by BlockSpec index mapping: q-head h reads kv-head h // group —
    K/V are never materialized per-q-head (the reference materializes via
    repeat_kv_heads, core/ops.cpp:2072);
  - causal + sliding-window + key-padding masks built from broadcasted
    iotas inside the kernel;
  - backward: one kernel per (b, h, q-block) computing dQ and accumulating
    dK/dV into revisited output blocks across the sequential ("arbitrary")
    grid dims — the standard dS = P∘(dO·Vᵀ − Δ) recomputation with the
    saved logsumexp.

For shapes the kernel doesn't support (S not a multiple of the block, tiny
D), ops/attention.py's XLA path is the fallback — same numerics, same mask
semantics (it is the oracle the kernel is tested against).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    """Pallas interpret mode off-TPU (CPU test mesh, SURVEY.md §4.6)."""
    return jax.default_backend() != "tpu"


# --------------------------------- forward ----------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, pad_ref, o_ref, lse_ref, *,
                scale, block_q, causal, window, S):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)           # [BQ, D]
    k = k_ref[0, 0].astype(jnp.float32)           # [S, D]
    v = v_ref[0, 0].astype(jnp.float32)           # [S, D]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale

    rows = (jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 0)
            + qi * block_q)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 1)
    mask = jnp.ones((block_q, S), jnp.bool_)
    if causal:
        mask &= cols <= rows
    if window is not None:
        mask &= cols > rows - window
    mask &= pad_ref[0] > 0                         # key padding [1, S]
    s = jnp.where(mask, s, NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)         # [BQ, 1]
    p = jnp.exp(s - m)
    p = jnp.where(mask, p, 0.0)                    # exp(NEG_INF-m) underflow
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.maximum(l, 1e-30)
    o = jax.lax.dot_general(p / l_safe, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0, 0] = o.astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)            # [BQ, 1]


def _fwd(q, k, v, padding_mask, *, scale, causal, window, block_q):
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    grid = (B, Hq, S // block_q)
    pad3 = padding_mask.reshape(B, 1, S)
    kernel = functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                               causal=causal, window=window, S=S)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, S, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel")),
        interpret=_interpret(),
    )(q, k, v, pad3)
    return out, lse


# --------------------------------- backward ---------------------------------

def _bwd_kernel(q_ref, k_ref, v_ref, pad_ref, o_ref, lse_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *, scale, block_q, causal, window,
                S, G):
    h = pl.program_id(1)
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)            # [BQ, D]
    k = k_ref[0, 0].astype(jnp.float32)            # [S, D]
    v = v_ref[0, 0].astype(jnp.float32)
    o = o_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                            # [BQ, 1]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    rows = (jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 0)
            + qi * block_q)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, S), 1)
    mask = jnp.ones((block_q, S), jnp.bool_)
    if causal:
        mask &= cols <= rows
    if window is not None:
        mask &= cols > rows - window
    mask &= pad_ref[0] > 0
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)             # [BQ, S]

    delta = jnp.sum(do * o, axis=-1, keepdims=True)        # [BQ, 1]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale                          # [BQ, S]

    dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)

    dk = jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [S, D]
    dv = jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)

    # dK/dV accumulate across the G q-heads of this kv-head and the q
    # blocks; first visit initializes.
    @pl.when(jnp.logical_and(h % G == 0, qi == 0))
    def _init():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    dk_ref[0, 0] += dk.astype(dk_ref.dtype)
    dv_ref[0, 0] += dv.astype(dv_ref.dtype)


def _bwd(scale, causal, window, block_q, res, g):
    q, k, v, padding_mask, out, lse = res
    do = g[0]  # cotangent of (out, lse); lse cotangent unused
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    grid = (B, Hq, S // block_q)
    pad3 = padding_mask.reshape(B, 1, S)
    kernel = functools.partial(_bwd_kernel, scale=scale, block_q=block_q,
                               causal=causal, window=window, S=S, G=G)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // G, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hq, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hkv, S, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, S, D), jnp.float32),
        ],
        # h and q-block dims revisit dK/dV blocks -> must run sequentially
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, pad3, out, lse, do)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), None


# ------------------------------- public API ---------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, padding_mask, scale, causal, window, block_q):
    out, _ = _fwd(q, k, v, padding_mask, scale=scale, causal=causal,
                  window=window, block_q=block_q)
    return out


def _flash_fwd(q, k, v, padding_mask, scale, causal, window, block_q):
    out, lse = _fwd(q, k, v, padding_mask, scale=scale, causal=causal,
                    window=window, block_q=block_q)
    return out, (q, k, v, padding_mask, out, lse)


def _flash_bwd(scale, causal, window, block_q, res, g):
    return _bwd(scale, causal, window, block_q, res, (g,))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *,
                    scale: Optional[float] = None,
                    is_causal: bool = True,
                    sliding_window: Optional[int] = None,
                    padding_mask: Optional[jnp.ndarray] = None,
                    attn_mask: Optional[jnp.ndarray] = None,
                    logits_dtype=jnp.float32,
                    block_q: int = 128) -> jnp.ndarray:
    """Drop-in for ops.attention.dot_product_attention (same signature).

    attn_mask (a precomputed [S, S] matrix) has no blockwise structure the
    kernel can exploit, so that case falls back to the XLA path — model code
    passes is_causal/sliding_window instead (gemma3 selects masks per layer
    by flags, not matrices, when using the flash impl).
    """
    from mobilefinetuner_tpu.ops.attention import dot_product_attention
    B, Hq, S, D = q.shape
    if (attn_mask is not None or S % block_q != 0
            or D not in (64, 128, 256)):
        return dot_product_attention(
            q, k, v, scale=scale, is_causal=is_causal,
            sliding_window=sliding_window, padding_mask=padding_mask,
            attn_mask=attn_mask, logits_dtype=logits_dtype)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if padding_mask is None:
        pad = jnp.ones((B, S), jnp.float32)
    else:
        pad = padding_mask.astype(jnp.float32)
    return _flash(q, k, v, pad, float(scale), bool(is_causal),
                  None if sliding_window is None else int(sliding_window),
                  int(block_q))
