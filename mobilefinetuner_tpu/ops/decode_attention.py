"""Pallas TPU decode attention: fused M=1 score+softmax+context kernel.

Single-token decode attention is bandwidth-bound: per layer it reads the
whole [B, H, T, D] K/V cache to produce one context row per head. The
XLA path (models/generate.py decode_step) lowers the two M=1 einsums +
softmax to VPU kLoop fusions that read the cache at ~245 GB/s on v5e
(~30% of the ~819 GB/s peak — a layout/emitter limit at M=1 shapes,
DESIGN.md §10); two XLA-level attempts to reach the MXU broke the cache's
dynamic-update-slice aliasing and regressed. This kernel attacks the same
floor from below: one pallas_call per layer streams each (batch,
kv-head-block)'s K and V cache slices through VMEM exactly once as whole
contiguous DMAs, computes scores + masked softmax + context in VMEM, and
writes the [G, D] context rows. The cache slices stay in their storage
dtype end to end (f32 accumulation via preferred_element_type, like the
XLA path), so the kernel moves the same bytes — just at DMA rate instead
of kLoop rate.

Shapes (GQA-general; GPT-2 is the G=1 case):
  q        [B, KV, G, D]   current-token queries, grouped by kv head
  k_cache  [B, KV, T, D]   T = P + N cache columns (whole-T VMEM blocks)
  v_cache  [B, KV, T, D]
  ok       [B, T]          attendable columns (validity AND sliding
                           window — caller composes, so Gemma's per-layer
                           global/local choice stays outside)
  -> ctx   [B, KV, G, D]   float32

Design notes:
  - whole-T blocks, no inner k-loop: decode caches are small (T·D ≤ ~1M
    elements at the supported sizes), so online softmax is unnecessary —
    the full [G, T] score row lives in registers/VMEM;
  - KVB kv-heads per program (largest divisor of KV fitting the VMEM
    budget): fewer, larger grid steps amortize per-program overhead when
    KV is large (GPT-2: 12 heads of [T, 64]) and keep DMAs big;
  - masked-out columns get NEG_INF scores; exp(NEG_INF - m) underflows to
    exactly 0, so no second mask pass is needed. A fully-masked row
    cannot occur (the current token's own column is always attendable);
  - no backward: generation is inference-only (the training path uses
    ops/flash_attention.py, which IS differentiable).

The XLA einsum path remains the oracle and the fallback for ineligible
shapes (T not sublane-aligned, VMEM overflow) and non-TPU backends
(interpret mode covers CPU tests).

Reference provenance: the reference framework's only KV-cache decode sits
in its excluded legacy tree (legacy/transformer/kv_cache.cpp, SURVEY.md
§2.10); this kernel is the TPU-native mechanical upgrade of that
capability (round-5 verdict item 1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

_VMEM_BUDGET = 12 * 2 ** 20


from mobilefinetuner_tpu.ops.pallas_util import tpu_call_params


def xla_reference(q, k_cache, v_cache, ok, scale):
    """The models/generate.py decode_step attention, verbatim semantics —
    the oracle the kernel is tested against and the comparison the
    microbench tool prices. ONE shared copy so the tests and the tool
    cannot drift from each other (generate.py keeps its own inline copy
    because its buffer structure is perf-fragile — DESIGN.md §10)."""
    s = jnp.einsum("bkgd,bktd->bkgt", q, k_cache,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(ok[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bkgt,bktd->bkgd", p.astype(v_cache.dtype), v_cache,
                      preferred_element_type=jnp.float32)


def shard_heads(KV: int, G: int, tp: int = 1):
    """Per-shard (KV, G) head counts under tp-way tensor parallelism —
    the ONE place the serve mesh's head-axis choice lives (the VMEM
    gates below and serve/sharding.ServeSharding both consult it, so
    the eligibility math can never disagree with the placement):

      KV % tp == 0  the pool's KV-head axis shards — each shard's
                    kernel sees KV // tp heads of its own page slice;
      G % tp == 0   (KV indivisible, GQA) the query-group axis shards —
                    each shard attends the WHOLE (replicated) pool with
                    G // tp query groups per KV head;
      neither       heads replicate: every shard pays the global counts.
    """
    tp = int(tp or 1)
    if tp > 1:
        if KV % tp == 0:
            return KV // tp, G
        if G % tp == 0:
            return KV, G // tp
    return KV, G


def pick_kvb(KV: int, T: int, D: int, itemsize: int, G: int = 1,
             tp: int = 1):
    """Largest divisor of KV whose double-buffered K+V whole-T blocks fit
    the VMEM budget, or None (caller falls back to XLA). Resident per grid
    step: 2 (K, V) x 2 (double buffer) x [KVB, T, D] storage-dtype
    blocks; the [KVB, G, D] q input and f32 ctx output blocks; the
    per-head [G, T] f32 score/prob rows; plus one T·D·4 slack term for
    the compiler's elementwise temps. The G-dependent terms keep large-G
    GQA shapes from passing the gate and overflowing VMEM at runtime
    (before them, only the K/V blocks were charged). tp > 1 charges the
    PER-SHARD head counts (shard_heads): under the serve mesh each
    shard's kernel streams only its own slice, so charging global heads
    would falsely gate the Pallas path off as tp grows."""
    KV, G = shard_heads(KV, G, tp)
    for kvb in range(KV, 0, -1):
        if KV % kvb:
            continue
        need = (4 * kvb * T * D * itemsize     # K+V, double-buffered
                + kvb * G * D * (itemsize + 4)  # q block + f32 ctx block
                + G * T * 4                     # [G, T] score/prob rows
                + T * D * 4)                    # elementwise-temp slack
        if need <= _VMEM_BUDGET:
            return kvb
    return None


def decode_eligible(KV: int, T: int, D: int, itemsize: int,
                    G: int = 1, tp: int = 1) -> bool:
    """T must be sublane-aligned (whole-T blocks are statically indexed,
    but the [T, D] tile still wants 8-row alignment); VMEM must fit
    (per-shard head counts when tp > 1 — see pick_kvb)."""
    return T % 8 == 0 and pick_kvb(KV, T, D, itemsize, G, tp) is not None


def _decode_kernel(q_ref, k_ref, v_ref, ok_ref, o_ref, *, scale, kvb):
    ok = ok_ref[0] > 0                                    # [1, T] (lanes)
    for j in range(kvb):                                  # static unroll
        k = k_ref[0, j]                                   # [T, D] storage
        v = v_ref[0, j]
        q = q_ref[0, j].astype(k.dtype)                   # [G, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [G, T]
        s = jnp.where(ok, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)                                # masked -> 0
        l = jnp.sum(p, axis=-1, keepdims=True)
        o_ref[0, j] = jax.lax.dot_general(
            (p / l).astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [G, D] f32


# --------------------------- block-paged variants ----------------------------
#
# The serving subsystem (serve/engine.py, DESIGN.md §16) replaces the
# per-request contiguous [B, KV, T, D] cache with one shared block pool
# [NB, L, KV, bT, D]: request r's logical column t lives at physical
# block tbl[r, t // bT], offset t % bT. Two readers of that layout:
#
#   paged_attention       XLA oracle: gather the slot's pages into a
#                         contiguous [S, M, KV, bT, D] view, then the
#                         same masked-softmax einsums as xla_reference.
#                         The gather MATERIALIZES the active cache once
#                         per layer per step — correct everywhere (it is
#                         what the CPU tests and the serve engine's
#                         default path run), but it moves the cache
#                         bytes twice.
#   paged_decode_attention Pallas kernel: the block table rides in as a
#                         scalar-prefetch operand, so each grid step
#                         DMAs ONE physical page straight from the pool
#                         (no materialized per-slot copy) and folds it
#                         into an online-softmax accumulator. This is
#                         the block-table-indexed upgrade of
#                         _decode_kernel: same VMEM streaming story,
#                         indirect page addressing instead of whole-T
#                         blocks.


def paged_attention(q, pool_k, pool_v, tbl, layer, ok, scale):
    """Block-paged decode attention, XLA path (the kernel's oracle).

    q       [S, KV, G, D]    current-token queries per slot
    pool_k  [NB, L, KV, bT, D]  shared block pools (all layers)
    pool_v  [NB, L, KV, bT, D]
    tbl     [S, M] int32     per-slot block table (unused rows -> the
                             reserved trash block 0; masked by ok)
    layer   scalar int32     which layer's pages to read
    ok      [S, M*bT] bool   attendable logical columns (occupancy AND
                             any sliding window — caller composes)
    -> ctx  [S, KV, G, D] float32
    """
    kc = pool_k[tbl, layer]                      # [S, M, KV, bT, D]
    vc = pool_v[tbl, layer]
    S, M, KV, bT, D = kc.shape
    G = q.shape[2]
    s = jnp.einsum("skgd,smktd->skgmt", q, kc,
                   preferred_element_type=jnp.float32) * scale
    s = s.reshape(S, KV, G, M * bT)
    s = jnp.where(ok[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).reshape(S, KV, G, M, bT)
    return jnp.einsum("skgmt,smktd->skgd", p.astype(vc.dtype), vc,
                      preferred_element_type=jnp.float32)


def paged_eligible(KV: int, G: int, bT: int, D: int,
                   itemsize: int, tp: int = 1) -> bool:
    """One page pair (K+V, double-buffered) + the per-slot q/ctx blocks
    and [G, bT] score rows must fit VMEM; bT must be sublane-aligned.
    tp > 1 charges PER-SHARD head counts (shard_heads): the sharded
    serve path runs the kernel under shard_map on each shard's pool
    slice, so the VMEM bill is the local one — global counts would be
    both too strict (KV-sharded pools) and, were the budget ever raised
    per-shard, unsafely lax the other way."""
    KV, G = shard_heads(KV, G, tp)
    need = (4 * KV * bT * D * itemsize          # K+V page, double-buffered
            + KV * G * D * (itemsize + 4)       # q block + f32 ctx block
            + 3 * KV * G * max(D, bT) * 4)      # o/m/l accumulators + p
    return bT % 8 == 0 and need <= _VMEM_BUDGET


def _paged_kernel(tbl_ref, lyr_ref, q_ref, k_ref, v_ref, ok_ref, o_ref,
                  o_acc, m_acc, l_acc, *, scale, kv):
    """Grid (S, M): slot-major, pages inner — the accumulators carry one
    slot's online softmax across its pages. A fully-masked page (e.g.
    beyond a sliding window) contributes exactly zero: probabilities are
    re-masked after the exp, so the NEG_INF-vs-NEG_INF cancellation in
    `s - m` cannot resurrect dead columns."""
    del tbl_ref, lyr_ref  # consumed by the index_maps, not the body
    m = pl.program_id(1)

    @pl.when(m == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    ok = ok_ref[0] > 0                                  # [bT] (lanes)
    for j in range(kv):                                 # static unroll
        k = k_ref[0, 0, j]                              # [bT, D] storage
        v = v_ref[0, 0, j]
        q = q_ref[0, j].astype(k.dtype)                 # [G, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [G, bT]
        s = jnp.where(ok, s, NEG_INF)
        m_prev = m_acc[j]                                # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(ok, jnp.exp(s - m_new), 0.0)       # [G, bT]
        o_acc[j] = alpha * o_acc[j] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_acc[j] = alpha * l_acc[j] + jnp.sum(p, axis=-1, keepdims=True)
        m_acc[j] = m_new

    @pl.when(m == pl.num_programs(1) - 1)
    def _finish():
        # the current token's own column is always attendable, so l > 0
        o_ref[0] = o_acc[...] / l_acc[...]


def paged_decode_attention(q, pool_k, pool_v, tbl, layer, ok, scale):
    """Pallas block-paged decode attention (shapes as paged_attention).
    The block table and layer index are scalar-prefetch operands: each
    (slot, page) grid step's index_map reads tbl to DMA the right
    physical [bT, D] page out of the pool — the cache is read once, at
    DMA rate, with no gathered per-slot copy. Caller must have checked
    paged_eligible."""
    S, KV, G, D = q.shape
    NB, L, _, bT, _ = pool_k.shape
    M = tbl.shape[1]
    if q.dtype != pool_k.dtype:
        raise ValueError(
            f"paged_decode_attention requires q.dtype == pool dtype "
            f"(got {q.dtype} vs {pool_k.dtype})")
    if not paged_eligible(KV, G, bT, D, pool_k.dtype.itemsize):
        raise ValueError(
            f"paged_decode_attention ineligible for KV={KV}, G={G}, "
            f"bT={bT}, D={D} (check paged_eligible before calling)")
    kernel = functools.partial(_paged_kernel, scale=scale, kv=KV)
    ok2 = ok.astype(jnp.int32).reshape(S, M * bT)
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,        # tbl, layer
        grid=(S, M),
        in_specs=[
            pl.BlockSpec((1, KV, G, D), lambda s, m, tbl, l: (s, 0, 0, 0)),
            pl.BlockSpec((1, 1, KV, bT, D),
                         lambda s, m, tbl, l: (tbl[s, m], l[0], 0, 0, 0)),
            pl.BlockSpec((1, 1, KV, bT, D),
                         lambda s, m, tbl, l: (tbl[s, m], l[0], 0, 0, 0)),
            pl.BlockSpec((1, bT), lambda s, m, tbl, l: (s, m)),
        ],
        out_specs=pl.BlockSpec((1, KV, G, D),
                               lambda s, m, tbl, l: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KV, G, D), jnp.float32),   # o accumulator
            pltpu.VMEM((KV, G, 1), jnp.float32),   # running max
            pltpu.VMEM((KV, G, 1), jnp.float32),   # running sum
        ],
    )
    # no dimension_semantics here: the page dimension must stay
    # sequential (the accumulators carry across it), which is the
    # compiler's default for grid_spec-style calls
    from mobilefinetuner_tpu.ops.pallas_util import interpret_mode
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, KV, G, D), jnp.float32),
        interpret=interpret_mode(),
    )(tbl.astype(jnp.int32), lyr, q, pool_k, pool_v, ok2)


def sharded_paged_attend(shardings):
    """paged_decode_attention under a serve (dp, tp) mesh, via shard_map.

    pallas_call is a custom call GSPMD cannot partition, so the sharded
    serve path wraps the UNCHANGED kernel in core/compat.shard_map and
    hands each shard its own operands:

      pool_k/pool_v  [NB, L, KV/tp, bT, D] per-shard head slice when
                     the KV axis shards (each shard DMAs only its own
                     pages), the whole pool otherwise (replicated);
      q / ctx        [S/dp, KV', G', D] — whichever head axis the
                     engine shards (shard_heads), slots split over dp;
      tbl / ok       replicated across tp (every shard walks the same
                     block tables), split over dp with their slots;
      layer          replicated scalar.

    Inside the body the kernel re-checks paged_eligible on its LOCAL
    shapes (tp defaults to 1 there — the division already happened),
    so the VMEM gate and the partitioning can never disagree.

    `shardings` is a serve/sharding.ServeSharding (duck-typed: mesh /
    dp / kv_shards / g_shards). Returns an attend(q, pool_k, pool_v,
    tbl, layer, ok, scale) drop-in for the paged_attention signature.
    """
    from jax.sharding import PartitionSpec as P

    from mobilefinetuner_tpu.core.compat import shard_map

    sh = shardings
    dp = "dp" if sh.dp > 1 else None
    kv_ax = "tp" if sh.kv_shards > 1 else None
    g_ax = "tp" if sh.g_shards > 1 else None
    q_spec = P(dp, kv_ax, g_ax, None)
    pool_spec = P(None, None, kv_ax, None, None)

    def attend(q, pool_k, pool_v, tbl, layer, ok, scale):
        def local(q_, pk_, pv_, tbl_, lyr_, ok_):
            return paged_decode_attention(q_, pk_, pv_, tbl_, lyr_, ok_,
                                          scale)

        # check_vma=False: the replicated-output proof doesn't see
        # through the kernel's custom call; the body is deterministic
        # per shard, so unmentioned axes are replicated by construction
        fn = shard_map(local, mesh=sh.mesh,
                       in_specs=(q_spec, pool_spec, pool_spec,
                                 P(dp, None), P(), P(dp, None)),
                       out_specs=q_spec, check_vma=False)
        return fn(q, pool_k, pool_v, tbl,
                  jnp.asarray(layer, jnp.int32), ok)

    return attend


def decode_attention(q, k_cache, v_cache, ok, scale):
    """Fused decode attention over a whole KV cache (shapes above).
    Caller must have checked decode_eligible for these shapes."""
    B, KV, G, D = q.shape
    T = k_cache.shape[2]
    if q.dtype != k_cache.dtype:
        # the kernel casts q to the cache dtype before the score dot
        # (generate.py always has them equal); a silent downcast of f32
        # queries against a bf16 cache would diverge from xla_reference
        raise ValueError(
            f"decode_attention requires q.dtype == cache dtype "
            f"(got {q.dtype} vs {k_cache.dtype})")
    kvb = pick_kvb(KV, T, D, k_cache.dtype.itemsize, G)
    if kvb is None or T % 8 != 0:
        raise ValueError(
            f"decode_attention ineligible for KV={KV}, T={T}, D={D}, "
            f"G={G}, itemsize={k_cache.dtype.itemsize} (check "
            f"decode_eligible before calling)")
    kernel = functools.partial(_decode_kernel, scale=scale, kvb=kvb)
    ok2 = ok.astype(jnp.int32).reshape(B, 1, T)
    return pl.pallas_call(
        kernel,
        grid=(B, KV // kvb),
        in_specs=[
            pl.BlockSpec((1, kvb, G, D), lambda b, k: (b, k, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kvb, T, D), lambda b, k: (b, k, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kvb, T, D), lambda b, k: (b, k, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, T), lambda b, k: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, kvb, G, D), lambda b, k: (b, k, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, KV, G, D), jnp.float32),
        **tpu_call_params("parallel", "parallel"),
    )(q, k_cache, v_cache, ok2)
