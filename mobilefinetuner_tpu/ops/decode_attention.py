"""Pallas TPU decode attention: fused M=1 score+softmax+context kernel.

Single-token decode attention is bandwidth-bound: per layer it reads the
whole [B, H, T, D] K/V cache to produce one context row per head. The
XLA path (models/generate.py decode_step) lowers the two M=1 einsums +
softmax to VPU kLoop fusions that read the cache at ~245 GB/s on v5e
(~30% of the ~819 GB/s peak — a layout/emitter limit at M=1 shapes,
DESIGN.md §10); two XLA-level attempts to reach the MXU broke the cache's
dynamic-update-slice aliasing and regressed. This kernel attacks the same
floor from below: one pallas_call per layer streams each (batch,
kv-head-block)'s K and V cache slices through VMEM exactly once as whole
contiguous DMAs, computes scores + masked softmax + context in VMEM, and
writes the [G, D] context rows. The cache slices stay in their storage
dtype end to end (f32 accumulation via preferred_element_type, like the
XLA path), so the kernel moves the same bytes — just at DMA rate instead
of kLoop rate.

Shapes (GQA-general; GPT-2 is the G=1 case):
  q        [B, KV, G, D]   current-token queries, grouped by kv head
  k_cache  [B, KV, T, D]   T = P + N cache columns (whole-T VMEM blocks)
  v_cache  [B, KV, T, D]
  ok       [B, T]          attendable columns (validity AND sliding
                           window — caller composes, so Gemma's per-layer
                           global/local choice stays outside)
  -> ctx   [B, KV, G, D]   float32

Design notes:
  - whole-T blocks, no inner k-loop: decode caches are small (T·D ≤ ~1M
    elements at the supported sizes), so online softmax is unnecessary —
    the full [G, T] score row lives in registers/VMEM;
  - KVB kv-heads per program (largest divisor of KV fitting the VMEM
    budget): fewer, larger grid steps amortize per-program overhead when
    KV is large (GPT-2: 12 heads of [T, 64]) and keep DMAs big;
  - masked-out columns get NEG_INF scores; exp(NEG_INF - m) underflows to
    exactly 0, so no second mask pass is needed. A fully-masked row
    cannot occur (the current token's own column is always attendable);
  - no backward: generation is inference-only (the training path uses
    ops/flash_attention.py, which IS differentiable).

The XLA einsum path remains the oracle and the fallback for ineligible
shapes (T not sublane-aligned, VMEM overflow) and non-TPU backends
(interpret mode covers CPU tests).

Reference provenance: the reference framework's only KV-cache decode sits
in its excluded legacy tree (legacy/transformer/kv_cache.cpp, SURVEY.md
§2.10); this kernel is the TPU-native mechanical upgrade of that
capability (round-5 verdict item 1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

_VMEM_BUDGET = 12 * 2 ** 20


from mobilefinetuner_tpu.ops.pallas_util import tpu_call_params


def xla_reference(q, k_cache, v_cache, ok, scale):
    """The models/generate.py decode_step attention, verbatim semantics —
    the oracle the kernel is tested against and the comparison the
    microbench tool prices. ONE shared copy so the tests and the tool
    cannot drift from each other (generate.py keeps its own inline copy
    because its buffer structure is perf-fragile — DESIGN.md §10)."""
    s = jnp.einsum("bkgd,bktd->bkgt", q, k_cache,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(ok[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bkgt,bktd->bkgd", p.astype(v_cache.dtype), v_cache,
                      preferred_element_type=jnp.float32)


def pick_kvb(KV: int, T: int, D: int, itemsize: int, G: int = 1):
    """Largest divisor of KV whose double-buffered K+V whole-T blocks fit
    the VMEM budget, or None (caller falls back to XLA). Resident per grid
    step: 2 (K, V) x 2 (double buffer) x [KVB, T, D] storage-dtype
    blocks; the [KVB, G, D] q input and f32 ctx output blocks; the
    per-head [G, T] f32 score/prob rows; plus one T·D·4 slack term for
    the compiler's elementwise temps. The G-dependent terms keep large-G
    GQA shapes from passing the gate and overflowing VMEM at runtime
    (before them, only the K/V blocks were charged)."""
    for kvb in range(KV, 0, -1):
        if KV % kvb:
            continue
        need = (4 * kvb * T * D * itemsize     # K+V, double-buffered
                + kvb * G * D * (itemsize + 4)  # q block + f32 ctx block
                + G * T * 4                     # [G, T] score/prob rows
                + T * D * 4)                    # elementwise-temp slack
        if need <= _VMEM_BUDGET:
            return kvb
    return None


def decode_eligible(KV: int, T: int, D: int, itemsize: int,
                    G: int = 1) -> bool:
    """T must be sublane-aligned (whole-T blocks are statically indexed,
    but the [T, D] tile still wants 8-row alignment); VMEM must fit."""
    return T % 8 == 0 and pick_kvb(KV, T, D, itemsize, G) is not None


def _decode_kernel(q_ref, k_ref, v_ref, ok_ref, o_ref, *, scale, kvb):
    ok = ok_ref[0] > 0                                    # [1, T] (lanes)
    for j in range(kvb):                                  # static unroll
        k = k_ref[0, j]                                   # [T, D] storage
        v = v_ref[0, j]
        q = q_ref[0, j].astype(k.dtype)                   # [G, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [G, T]
        s = jnp.where(ok, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)                                # masked -> 0
        l = jnp.sum(p, axis=-1, keepdims=True)
        o_ref[0, j] = jax.lax.dot_general(
            (p / l).astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [G, D] f32


def decode_attention(q, k_cache, v_cache, ok, scale):
    """Fused decode attention over a whole KV cache (shapes above).
    Caller must have checked decode_eligible for these shapes."""
    B, KV, G, D = q.shape
    T = k_cache.shape[2]
    if q.dtype != k_cache.dtype:
        # the kernel casts q to the cache dtype before the score dot
        # (generate.py always has them equal); a silent downcast of f32
        # queries against a bf16 cache would diverge from xla_reference
        raise ValueError(
            f"decode_attention requires q.dtype == cache dtype "
            f"(got {q.dtype} vs {k_cache.dtype})")
    kvb = pick_kvb(KV, T, D, k_cache.dtype.itemsize, G)
    if kvb is None or T % 8 != 0:
        raise ValueError(
            f"decode_attention ineligible for KV={KV}, T={T}, D={D}, "
            f"G={G}, itemsize={k_cache.dtype.itemsize} (check "
            f"decode_eligible before calling)")
    kernel = functools.partial(_decode_kernel, scale=scale, kvb=kvb)
    ok2 = ok.astype(jnp.int32).reshape(B, 1, T)
    return pl.pallas_call(
        kernel,
        grid=(B, KV // kvb),
        in_specs=[
            pl.BlockSpec((1, kvb, G, D), lambda b, k: (b, k, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kvb, T, D), lambda b, k: (b, k, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kvb, T, D), lambda b, k: (b, k, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, T), lambda b, k: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, kvb, G, D), lambda b, k: (b, k, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, KV, G, D), jnp.float32),
        **tpu_call_params("parallel", "parallel"),
    )(q, k_cache, v_cache, ok2)
