"""Inverted dropout, shared by every site that needs train-mode masking
(model embd/resid dropout, attention-probs dropout, the LoRA branch).
Reference: core/ops.cpp:2670 dropout; PEFT branch semantics in
nn/lora_linear.cpp:47-106."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def inverted_dropout(x, rate: float, rng):
    """x scaled by 1/keep on surviving elements; identity when rate == 0
    or rng is None (eval mode)."""
    if rate <= 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
