"""Pallas fused cross-entropy head: logits never touch HBM.

The chunked CE (ops/loss.py) bounds peak memory by materializing one
[B, chunk, V] logits block per scan step — but at Gemma's 262k vocab even
one chunk's logits are hundreds of MB of f32 that XLA writes, re-reads for
the two logsumexp passes, and (inside jax.checkpoint) writes and reads
AGAIN in the backward: the measured ~6 ms/step of bandwidth-bound softmax
the round-3 verdict flagged (reference standard: the one-pass analytic CE
backward in core/lm_loss.cpp:19-103, which also never re-materializes).

This kernel streams the vocabulary in VMEM-resident tiles instead:

  forward  — grid over V tiles (sequential); each step computes one
             [R, BV] logits tile on the MXU, folds it into running
             online-softmax statistics (m, s) and picks up the gold
             logit by iota-compare, all in VMEM scratch. HBM traffic is
             ONE read of W per chunk; logits never leave the chip.
             Returns (lse, gold) per row — exactly what the NLL needs.
  backward — split in two kernels so dead-code elimination can drop the
             dW pass when the head is FROZEN (LoRA: W's cotangent is
             never consumed, so only the dh kernel survives):
      dh:  same V-tile loop, recomputes each logits tile, forms
           coef = dlse*p + dgold*onehot, accumulates coef @ W_tile into
           a [R, H] VMEM scratch.
      dW:  grid over V tiles, each program writes its [BV, H] tile of
           dW = coef^T @ h.

The custom_vjp saves only (h, W, labels, lse) — O(R) beyond the inputs.
Numerics match ops/loss.py's _token_nll form (f32 max-shifted logsumexp)
up to tile-order rounding; tests/test_fused_ce.py pins both the forward
and the gradients to the XLA oracle.

Dispatch outcome (measured, v5e round 4): the kernel is numerically
exact but ~6% SLOWER than the XLA path at Gemma-270M train shapes and at
parity at Gemma-1B — XLA's consumer fusions already reduce the chunk
logits against the matmul output well enough that there is no HBM
traffic left to win, and the kernel pays per-tile loop overhead
(DESIGN.md §5a has the numbers). chunked_lm_cross_entropy's "auto"
therefore resolves to XLA; pass use_fused_kernel=True to force this
kernel (tests do, in interpret mode; re-measure if the compiler or the
shapes change).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from mobilefinetuner_tpu.ops.pallas_util import tpu_call_params

_VMEM_BUDGET = 14 * 2 ** 20   # leave headroom under the 16 MB scoped limit


def pick_block_v(V: int, R: int = 512, H: int = 1152,
                 itemsize: int = 2, r_pad: int = 0) -> Optional[int]:
    """Largest lane-aligned vocab tile dividing V that fits the VMEM
    budget (None = ineligible). Resident per grid step of the dh kernel
    (the largest of the three): the [R, H] hidden block in the STORAGE
    dtype (`itemsize` — 2 for bf16, 4 for f32), the double-buffered
    [BV, H] weight tile, the [R, BV] f32 logits tile (the coef temp
    aliases it after consumption), and the dh kernel's [R, H] f32
    accumulator scratch AND output block. Budget calibrated on v5e:
    (R=1024, H=640, bv=1024) counts 13.4 MB here, compiles and runs;
    bv=2048 at the same shape counts 20.2 MB (actual scoped allocation
    failed at 16.8 MB) and is rejected.

    r_pad > 0 is the head-adapter epilogue variant (DESIGN.md §17): it
    adds the [R, r_pad] xa slab plus the dh kernel's [R, r_pad] f32 axa
    accumulator scratch AND dxa output block (fixed — the same
    scratch+output double-count as the base dh accounting above), and
    the double-buffered [BV, r_pad] bt tile + [BV, r_pad] f32 dbt output
    (per tile)."""
    fixed = R * H * itemsize + 2 * R * H * 4 + 6 * R * 4 \
        + r_pad * (R * itemsize + 2 * R * 4)
    per_bv = 2 * H * itemsize + R * 4 + r_pad * (2 * itemsize + 4)
    for bv in (2048, 1024, 512, 256, 128):
        if V % bv == 0 and fixed + bv * per_bv <= _VMEM_BUDGET:
            return bv
    return None


def fused_ce_eligible(R: int, V: int, H: int = 1152,
                      itemsize: int = 2) -> bool:
    """Rows must be sublane-aligned; V must tile lane-aligned within the
    VMEM budget for this (R, H, storage itemsize)."""
    return R % 8 == 0 and pick_block_v(V, R, H, itemsize) is not None


# rank dim of the head-adapter operands padded to one lane tile (the
# same alignment trick as ops/lora_fused.R_PAD; r <= 128 covers every
# LoRA rank in this tree)
LORA_R_PAD = 128


def fused_ce_lora_eligible(R: int, V: int, H: int = 1152, r: int = 8,
                           itemsize: int = 2) -> bool:
    """Eligibility of the head-adapter epilogue variant: the base gate
    plus rank ≤ the lane pad and the xa/bt slabs fitting the budget."""
    return (R % 8 == 0 and 0 < r <= LORA_R_PAD
            and pick_block_v(V, R, H, itemsize, LORA_R_PAD) is not None)


def _pick_block_v_or_raise(V, R, H, itemsize) -> int:
    bv = pick_block_v(V, R, H, itemsize)
    if bv is None:
        raise ValueError(
            f"fused CE kernel ineligible for R={R}, V={V}, H={H}, "
            f"itemsize={itemsize} (check fused_ce_eligible before "
            f"calling)")
    return bv


# --------------------------------- forward ----------------------------------

def _fwd_kernel(h_ref, w_ref, lab_ref, lse_ref, gold_ref, m_sc, s_sc,
                g_sc, *, block_v, n_tiles):
    vi = pl.program_id(0)
    col0 = vi * block_v

    @pl.when(vi == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, -jnp.inf)
        s_sc[:] = jnp.zeros_like(s_sc)
        g_sc[:] = jnp.zeros_like(g_sc)

    h = h_ref[:]                                   # [R, H] storage dtype
    w = w_ref[:]                                   # [BV, H]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)        # [R, BV] f32
    R, BV = logits.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (R, BV), 1) + col0
    hit = cols == lab_ref[:]                       # [R, BV] (lab [R, 1])
    m = m_sc[:]
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
    s_sc[:] = s_sc[:] * jnp.exp(m - m_new) \
        + jnp.sum(jnp.exp(logits - m_new), axis=-1, keepdims=True)
    m_sc[:] = m_new
    g_sc[:] = g_sc[:] + jnp.sum(jnp.where(hit, logits, 0.0), axis=-1,
                                keepdims=True)

    @pl.when(vi == n_tiles - 1)
    def _fin():
        lse_ref[:] = m_sc[:] + jnp.log(s_sc[:])
        gold_ref[:] = g_sc[:]


def _fwd(h2, w, labels2):
    R, H = h2.shape
    V = w.shape[0]
    bv = _pick_block_v_or_raise(V, R, H, h2.dtype.itemsize)
    n = V // bv
    kernel = functools.partial(_fwd_kernel, block_v=bv, n_tiles=n)
    call = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((R, H), lambda vi: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), lambda vi: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((R, 1), lambda vi: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), lambda vi: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
        ],
        **tpu_call_params("arbitrary"),
    )
    # phase label for profiler traces / HLO metadata (DESIGN.md §13)
    with jax.named_scope("loss"), jax.named_scope("fused_ce_fwd"):
        lse, gold = call(h2, w, labels2)
    return lse[:, 0], gold[:, 0]


# --------------------------------- backward ---------------------------------

def _dh_kernel(h_ref, w_ref, lab_ref, lse_ref, dlse_ref, dgold_ref,
               dh_ref, acc_sc, *, block_v, n_tiles):
    vi = pl.program_id(0)
    col0 = vi * block_v

    @pl.when(vi == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    h = h_ref[:]
    w = w_ref[:]                                    # [BV, H]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)         # [R, BV]
    R, BV = logits.shape
    p = jnp.exp(logits - lse_ref[:])                # [R, BV]
    cols = jax.lax.broadcasted_iota(jnp.int32, (R, BV), 1) + col0
    hit = cols == lab_ref[:]
    coef = dlse_ref[:] * p + jnp.where(hit, dgold_ref[:], 0.0)
    acc_sc[:] = acc_sc[:] + jax.lax.dot_general(
        coef.astype(w.dtype), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [R, H]

    @pl.when(vi == n_tiles - 1)
    def _fin():
        dh_ref[:] = acc_sc[:]


def _dw_kernel(h_ref, w_ref, lab_ref, lse_ref, dlse_ref, dgold_ref,
               dw_ref, *, block_v):
    vi = pl.program_id(0)
    col0 = vi * block_v
    h = h_ref[:]
    w = w_ref[:]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    R, BV = logits.shape
    p = jnp.exp(logits - lse_ref[:])
    cols = jax.lax.broadcasted_iota(jnp.int32, (R, BV), 1) + col0
    hit = cols == lab_ref[:]
    coef = dlse_ref[:] * p + jnp.where(hit, dgold_ref[:], 0.0)
    dw_ref[:] = jax.lax.dot_general(
        coef.astype(h.dtype), h, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [BV, H]


def _bwd_dh(h2, w, labels2, lse2, dlse2, dgold2):
    R, H = h2.shape
    V = w.shape[0]
    bv = _pick_block_v_or_raise(V, R, H, h2.dtype.itemsize)
    n = V // bv
    kernel = functools.partial(_dh_kernel, block_v=bv, n_tiles=n)
    row = lambda vi: (0, 0)
    call = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((R, H), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, H), row, memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, H), jnp.float32),
        scratch_shapes=[pltpu.VMEM((R, H), jnp.float32)],
        **tpu_call_params("arbitrary"),
    )
    with jax.named_scope("loss"), jax.named_scope("fused_ce_bwd_dh"):
        return call(h2, w, labels2, lse2, dlse2, dgold2)


def _bwd_dw(h2, w, labels2, lse2, dlse2, dgold2):
    R, H = h2.shape
    V = w.shape[0]
    bv = _pick_block_v_or_raise(V, R, H, h2.dtype.itemsize)
    n = V // bv
    kernel = functools.partial(_dw_kernel, block_v=bv)
    row = lambda vi: (0, 0)
    call = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((R, H), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bv, H), lambda vi: (vi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((V, H), jnp.float32),
        **tpu_call_params("arbitrary"),
    )
    with jax.named_scope("loss"), jax.named_scope("fused_ce_bwd_dw"):
        return call(h2, w, labels2, lse2, dlse2, dgold2)


# ------------------------------ public entry --------------------------------

@jax.custom_vjp
def fused_ce_rows(hidden2d, w, labels) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(lse [R], gold_logit [R]) for rows of hidden states against the
    [V, H] head table; labels must be IN-RANGE (caller substitutes 0 for
    ignore_index positions and masks the NLL outside). Differentiable in
    hidden2d and w (the dW pass is DCE'd when w's cotangent is unused)."""
    lse, gold = _fwd(hidden2d, w, labels.reshape(-1, 1))
    return lse, gold


def _vjp_fwd(hidden2d, w, labels):
    labels2 = labels.reshape(-1, 1)
    lse, gold = _fwd(hidden2d, w, labels2)
    return (lse, gold), (hidden2d, w, labels2, lse)


def _vjp_bwd(res, cts):
    hidden2d, w, labels2, lse = res
    dlse, dgold = cts
    lse2 = lse.reshape(-1, 1)
    dlse2 = dlse.reshape(-1, 1).astype(jnp.float32)
    dgold2 = dgold.reshape(-1, 1).astype(jnp.float32)
    dh = _bwd_dh(hidden2d, w, labels2, lse2, dlse2, dgold2)
    dw = _bwd_dw(hidden2d, w, labels2, lse2, dlse2, dgold2)
    return (dh.astype(hidden2d.dtype), dw.astype(w.dtype), None)


fused_ce_rows.defvjp(_vjp_fwd, _vjp_bwd)


# -------------------- head-adapter epilogue variant --------------------------
#
# LoRA on the lm_head (DESIGN.md §17): logits = h @ Wᵀ + scale·(h@A)@B.
# The rank-r bottleneck xa = scale·(h@A) [R, r] is computed by XLA (it is
# tiny); the [R, V] delta — hundreds of MB at Gemma's 262k vocab — folds
# into this kernel's vocab-tile loop instead of ever being materialized:
# each grid step adds xa @ bt_tileᵀ (bt = Bᵀ [V, r], row-tiled like W) to
# its logits tile in VMEM. The backward mirrors the base kernels: the dh
# pass also accumulates dxa = Σ coef @ bt_tile in a [R, r] scratch, and
# the dw pass additionally writes its [BV, r] tile of dbt = coefᵀ @ xa.
# The rank dim is zero-padded to LORA_R_PAD lanes (see ops/lora_fused).


def _lora_logits(logits, xa, bt):
    return logits + jax.lax.dot_general(
        xa, bt, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fwd_kernel_lora(h_ref, w_ref, xa_ref, bt_ref, lab_ref, lse_ref,
                     gold_ref, m_sc, s_sc, g_sc, *, block_v, n_tiles):
    vi = pl.program_id(0)
    col0 = vi * block_v

    @pl.when(vi == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, -jnp.inf)
        s_sc[:] = jnp.zeros_like(s_sc)
        g_sc[:] = jnp.zeros_like(g_sc)

    h = h_ref[:]
    w = w_ref[:]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    logits = _lora_logits(logits, xa_ref[:], bt_ref[:])
    R, BV = logits.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (R, BV), 1) + col0
    hit = cols == lab_ref[:]
    m = m_sc[:]
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
    s_sc[:] = s_sc[:] * jnp.exp(m - m_new) \
        + jnp.sum(jnp.exp(logits - m_new), axis=-1, keepdims=True)
    m_sc[:] = m_new
    g_sc[:] = g_sc[:] + jnp.sum(jnp.where(hit, logits, 0.0), axis=-1,
                                keepdims=True)

    @pl.when(vi == n_tiles - 1)
    def _fin():
        lse_ref[:] = m_sc[:] + jnp.log(s_sc[:])
        gold_ref[:] = g_sc[:]


def _dh_kernel_lora(h_ref, w_ref, xa_ref, bt_ref, lab_ref, lse_ref,
                    dlse_ref, dgold_ref, dh_ref, dxa_ref, acc_sc, axa_sc,
                    *, block_v, n_tiles):
    vi = pl.program_id(0)
    col0 = vi * block_v

    @pl.when(vi == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        axa_sc[:] = jnp.zeros_like(axa_sc)

    h = h_ref[:]
    w = w_ref[:]
    bt = bt_ref[:]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    logits = _lora_logits(logits, xa_ref[:], bt)
    R, BV = logits.shape
    p = jnp.exp(logits - lse_ref[:])
    cols = jax.lax.broadcasted_iota(jnp.int32, (R, BV), 1) + col0
    hit = cols == lab_ref[:]
    coef = dlse_ref[:] * p + jnp.where(hit, dgold_ref[:], 0.0)
    coef_s = coef.astype(w.dtype)
    acc_sc[:] = acc_sc[:] + jax.lax.dot_general(
        coef_s, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [R, H]
    axa_sc[:] = axa_sc[:] + jax.lax.dot_general(
        coef_s, bt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [R, r_pad]

    @pl.when(vi == n_tiles - 1)
    def _fin():
        dh_ref[:] = acc_sc[:]
        dxa_ref[:] = axa_sc[:]


def _dw_kernel_lora(h_ref, w_ref, xa_ref, bt_ref, lab_ref, lse_ref,
                    dlse_ref, dgold_ref, dw_ref, dbt_ref, *, block_v):
    vi = pl.program_id(0)
    col0 = vi * block_v
    h = h_ref[:]
    w = w_ref[:]
    xa = xa_ref[:]
    logits = jax.lax.dot_general(
        h, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    logits = _lora_logits(logits, xa, bt_ref[:])
    R, BV = logits.shape
    p = jnp.exp(logits - lse_ref[:])
    cols = jax.lax.broadcasted_iota(jnp.int32, (R, BV), 1) + col0
    hit = cols == lab_ref[:]
    coef = dlse_ref[:] * p + jnp.where(hit, dgold_ref[:], 0.0)
    dw_ref[:] = jax.lax.dot_general(
        coef.astype(h.dtype), h, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [BV, H]
    dbt_ref[:] = jax.lax.dot_general(
        coef.astype(xa.dtype), xa, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)         # [BV, r_pad]


def _pad_lora(xa, bt, dtype):
    rp = LORA_R_PAD - xa.shape[1]
    return (jnp.pad(xa.astype(dtype), ((0, 0), (0, rp))),
            jnp.pad(bt.astype(dtype), ((0, 0), (0, rp))))


def _pick_lora_bv_or_raise(V, R, H, itemsize) -> int:
    bv = pick_block_v(V, R, H, itemsize, LORA_R_PAD)
    if bv is None:
        raise ValueError(
            f"fused CE lora kernel ineligible for R={R}, V={V}, H={H}, "
            f"itemsize={itemsize} (check fused_ce_lora_eligible before "
            f"calling)")
    return bv


def _fwd_lora(h2, w, xa, bt, labels2):
    R, H = h2.shape
    V = w.shape[0]
    bv = _pick_lora_bv_or_raise(V, R, H, h2.dtype.itemsize)
    n = V // bv
    xa_p, bt_p = _pad_lora(xa, bt, h2.dtype)
    kernel = functools.partial(_fwd_kernel_lora, block_v=bv, n_tiles=n)
    row = lambda vi: (0, 0)
    call = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((R, H), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, LORA_R_PAD), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, LORA_R_PAD), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
        ],
        **tpu_call_params("arbitrary"),
    )
    with jax.named_scope("loss"), jax.named_scope("fused_ce_lora_fwd"):
        lse, gold = call(h2, w, xa_p, bt_p, labels2)
    return lse[:, 0], gold[:, 0]


def _bwd_dh_lora(h2, w, xa, bt, labels2, lse2, dlse2, dgold2):
    R, H = h2.shape
    V = w.shape[0]
    bv = _pick_lora_bv_or_raise(V, R, H, h2.dtype.itemsize)
    n = V // bv
    xa_p, bt_p = _pad_lora(xa, bt, h2.dtype)
    kernel = functools.partial(_dh_kernel_lora, block_v=bv, n_tiles=n)
    row = lambda vi: (0, 0)
    call = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((R, H), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, LORA_R_PAD), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, LORA_R_PAD), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((R, H), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, LORA_R_PAD), row, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, H), jnp.float32),
            jax.ShapeDtypeStruct((R, LORA_R_PAD), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((R, H), jnp.float32),
            pltpu.VMEM((R, LORA_R_PAD), jnp.float32),
        ],
        **tpu_call_params("arbitrary"),
    )
    with jax.named_scope("loss"), jax.named_scope("fused_ce_lora_bwd_dh"):
        dh, dxa_p = call(h2, w, xa_p, bt_p, labels2, lse2, dlse2, dgold2)
    return dh, dxa_p[:, :xa.shape[1]]


def _bwd_dw_lora(h2, w, xa, bt, labels2, lse2, dlse2, dgold2):
    R, H = h2.shape
    V = w.shape[0]
    bv = _pick_lora_bv_or_raise(V, R, H, h2.dtype.itemsize)
    n = V // bv
    xa_p, bt_p = _pad_lora(xa, bt, h2.dtype)
    kernel = functools.partial(_dw_kernel_lora, block_v=bv)
    row = lambda vi: (0, 0)
    call = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((R, H), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, LORA_R_PAD), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, LORA_R_PAD), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), row, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bv, H), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, LORA_R_PAD), lambda vi: (vi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((V, H), jnp.float32),
            jax.ShapeDtypeStruct((V, LORA_R_PAD), jnp.float32),
        ],
        **tpu_call_params("arbitrary"),
    )
    with jax.named_scope("loss"), jax.named_scope("fused_ce_lora_bwd_dw"):
        dw, dbt_p = call(h2, w, xa_p, bt_p, labels2, lse2, dlse2, dgold2)
    return dw, dbt_p[:, :xa.shape[1]]


@jax.custom_vjp
def fused_ce_rows_lora(hidden2d, w, labels, xa,
                       bt) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """fused_ce_rows with the head-adapter delta folded into the tile
    loop: logits_tile = h @ w_tileᵀ + xa @ bt_tileᵀ. xa [R, r] is the
    SCALE-FOLDED rank-r bottleneck (scale·(h@A), compute dtype); bt = Bᵀ
    [V, r]. Differentiable in hidden2d, w, xa, and bt — the A/B/scale
    chain outside composes through plain XLA autodiff."""
    lse, gold = _fwd_lora(hidden2d, w, xa, bt, labels.reshape(-1, 1))
    return lse, gold


def _vjp_fwd_lora(hidden2d, w, labels, xa, bt):
    labels2 = labels.reshape(-1, 1)
    lse, gold = _fwd_lora(hidden2d, w, xa, bt, labels2)
    return (lse, gold), (hidden2d, w, labels2, lse, xa, bt)


def _vjp_bwd_lora(res, cts):
    hidden2d, w, labels2, lse, xa, bt = res
    dlse, dgold = cts
    lse2 = lse.reshape(-1, 1)
    dlse2 = dlse.reshape(-1, 1).astype(jnp.float32)
    dgold2 = dgold.reshape(-1, 1).astype(jnp.float32)
    dh, dxa = _bwd_dh_lora(hidden2d, w, xa, bt, labels2, lse2, dlse2,
                           dgold2)
    dw, dbt = _bwd_dw_lora(hidden2d, w, xa, bt, labels2, lse2, dlse2,
                           dgold2)
    return (dh.astype(hidden2d.dtype), dw.astype(w.dtype), None,
            dxa.astype(xa.dtype), dbt.astype(bt.dtype))


fused_ce_rows_lora.defvjp(_vjp_fwd_lora, _vjp_bwd_lora)


def head_bottleneck(hidden2d, lora_head):
    """(xa, bt) kernel operands from a head-adapter entry {A [H, r],
    B [r, V], scale}: xa = scale·(h@A) f32-accumulated then cast to the
    compute dtype, bt = Bᵀ. ONE copy of the scale-folding/stop-gradient
    convention (models/lora_apply semantics) shared by the kernel path
    and ops/loss.py's XLA fallback."""
    A = lora_head["A"].astype(hidden2d.dtype)
    B = lora_head["B"]
    scale = jax.lax.stop_gradient(
        jnp.asarray(lora_head["scale"]).astype(jnp.float32))
    xa = jnp.einsum("rh,hk->rk", hidden2d, A,
                    preferred_element_type=jnp.float32)
    xa = (xa * scale).astype(hidden2d.dtype)
    return xa, B.T.astype(hidden2d.dtype)


def fused_ce_nll_sum(hidden, lm_head_w, labels, ignore_index: int,
                     lora_head=None,
                     branch_hidden=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum_nll, valid_count) over ONE already-shifted chunk
    [B, chunk, H] / [B, chunk] — the scan-body form ops/loss.py uses.
    lora_head: optional {A, B, scale} head-adapter entry folded into the
    kernel's vocab-tile loop (the [R, V] delta never materializes).
    branch_hidden: the adapter branch's input when it differs from
    `hidden` — train-mode LoRA dropout drops the branch copy only, PEFT
    semantics (models/lora_apply docstring); base logits always read
    the undropped hidden."""
    B, C, H = hidden.shape
    R = B * C
    lab = labels.reshape(R)
    valid = lab != ignore_index
    safe = jnp.where(valid, lab, 0)
    h2 = hidden.reshape(R, H)
    if lora_head is None:
        lse, gold = fused_ce_rows(h2, lm_head_w, safe)
    else:
        hb2 = h2 if branch_hidden is None \
            else branch_hidden.reshape(R, H)
        xa, bt = head_bottleneck(hb2, lora_head)
        lse, gold = fused_ce_rows_lora(h2, lm_head_w, safe, xa, bt)
    nll = jnp.where(valid, lse - gold, 0.0)
    return nll.sum(), valid.sum()
