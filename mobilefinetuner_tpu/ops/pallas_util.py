"""Shared plumbing for the Pallas kernels (flash attention, fused CE,
decode attention)."""

from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """Pallas interpret mode off-TPU (CPU test mesh, SURVEY.md §4.6) —
    the ONE copy of the policy every kernel consults."""
    return jax.default_backend() != "tpu"
