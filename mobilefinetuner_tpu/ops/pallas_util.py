"""Shared plumbing for the Pallas kernels (flash attention, fused CE,
decode attention)."""

from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """Pallas interpret mode off-TPU (CPU test mesh, SURVEY.md §4.6) —
    the ONE copy of the policy every kernel consults."""
    return jax.default_backend() != "tpu"


def tpu_call_params(*dimension_semantics: str) -> dict:
    """The compiler_params + interpret kwargs every pallas_call in this
    tree passes — one copy of the dimension-semantics plumbing so a
    kernel cannot set semantics without also consulting the interpret
    policy (and one copy of the CompilerParams/TPUCompilerParams rename
    shim across jax versions). Returns a dict to splat into
    pl.pallas_call."""
    from jax.experimental.pallas import tpu as pltpu
    params_cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return dict(
        compiler_params=params_cls(
            dimension_semantics=dimension_semantics),
        interpret=interpret_mode())
