"""Attention: XLA reference implementation with causal + sliding-window +
padding masks and GQA. A Pallas flash kernel (ops/flash_attention.py) is the
fast path; this module is the always-correct fallback and the numerics oracle
the kernel is tested against.

Replaces the reference's two attention paths
(operators/finetune_ops/core/memory_efficient_attention.cpp — forward-only
streaming softmax — and the per-model scalar score loops in
graph/gpt2_model.cpp:669-711 / graph/gemma_model.cpp:358-520). Unlike the
reference's memory-efficient path, this one is differentiable (SURVEY.md
§2.12.1: the reference's GPT-2 default attention severs the autograd graph;
we do NOT replicate that bug — JAX autodiff covers every path).

Everything here is jit-traceable with static shapes: masks are built with
broadcasted iotas (no data-dependent control flow).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def causal_mask(q_len: int, kv_len: int,
                sliding_window: Optional[int] = None) -> jnp.ndarray:
    """[q_len, kv_len] bool mask, True = attend.

    Causal: key j visible to query i iff j <= i (+ offset when kv_len >
    q_len, i.e. with a prefix/KV cache). Sliding window additionally
    requires j > i - window (reference: gemma_model.h:145
    `build_sliding_mask`, window default 512 = gemma_model.h:26).
    """
    offset = kv_len - q_len
    qi = jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 0) + offset
    kj = jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 1)
    mask = kj <= qi
    if sliding_window is not None:
        mask &= kj > qi - sliding_window
    return mask


def dot_product_attention(
        q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
        *,
        scale: Optional[float] = None,
        is_causal: bool = True,
        sliding_window: Optional[int] = None,
        padding_mask: Optional[jnp.ndarray] = None,
        attn_mask: Optional[jnp.ndarray] = None,
        logits_dtype=jnp.float32,
        attn_dropout: float = 0.0,
        attn_dropout_rng: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Scaled dot-product attention with GQA.

    q: [B, Hq, S, D]; k, v: [B, Hkv, S, D] with Hq % Hkv == 0 — GQA is
    expressed by reshaping q into [B, Hkv, G, S, D] groups rather than
    materializing repeated K/V heads (the reference materializes via
    `repeat_kv_heads`, core/ops.cpp:2072; on TPU the einsum broadcast keeps
    K/V in their small layout and saves HBM).
    padding_mask: [B, S] bool/0-1, True/1 = real token.
    attn_mask: precomputed [q, k] bool mask (True = attend) used INSTEAD of
    the causal/sliding construction (e.g. Gemma's per-layer selected mask);
    combined with padding_mask if both given.
    scale: default 1/sqrt(D). (Gemma uses query_pre_attn_scalar^-0.5 —
    pass it explicitly; gemma_model.h:33.)
    """
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    G = Hq // Hkv
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    qg = q.reshape(B, Hkv, G, S, D)
    scores = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k,
                        preferred_element_type=logits_dtype)
    scores = scores.astype(logits_dtype) * jnp.asarray(scale, logits_dtype)

    neg = jnp.asarray(jnp.finfo(logits_dtype).min, logits_dtype)
    if attn_mask is not None:
        scores = jnp.where(attn_mask[None, None, None, :, :], scores, neg)
    elif is_causal or sliding_window is not None:
        m = causal_mask(S, S, sliding_window if sliding_window else None)
        scores = jnp.where(m[None, None, None, :, :], scores, neg)
    if padding_mask is not None:
        pm = padding_mask.astype(bool)
        scores = jnp.where(pm[:, None, None, None, :], scores, neg)

    probs = jax.nn.softmax(scores, axis=-1)
    # dropout on attention weights, HF train-mode semantics
    # (reference: core/ops.cpp:2670 applied to probs)
    from mobilefinetuner_tpu.ops.dropout import inverted_dropout
    probs = inverted_dropout(probs, attn_dropout, attn_dropout_rng)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32).astype(v.dtype)
    return out.reshape(B, Hq, S, D)


def resolve_impl(S: int, D: int) -> str:
    """The 'auto' dispatch rule, from TPU v5e measurements: the flash
    kernel wins from S >= 512 at small head dim (GPT-2, D=64) and from
    S >= 2048 at large head dim (Gemma GQA layout, D=256), thanks to
    causal/sliding-window block skipping. Round-4 retune, measured
    END-TO-END on the train step (the serial-chain microbench hits a
    ~0.7 ms dispatch floor on the tunneled platform and cannot resolve
    ops this small): GPT-2s S=512 flash 119.8k vs xla 99.7k tok/s
    (+20%), S=256 flash 121.3k vs xla 136.6k (-11%, XLA keeps it);
    Gemma-270M S=512 flash 44.2k vs xla 47.1k (-6%, threshold stays
    2048; S=1024 was 0.92-0.98x in round 3). With train-mode attention
    dropout the gap explodes (4.6x at S=1024, 6.6x at S=2048): the XLA
    path materializes + RNGs a [B, H, S, S] probs mask while the kernel
    hashes its keep bits in-register (flash_attention.py _keep_mask).
    Shared by attention() and the model blocks that branch on the impl
    themselves (models/gemma3.py) — retune in ONE place.
    """
    return "flash" if S >= (512 if D <= 128 else 2048) else "xla"


def attention(q, k, v, *, impl: str = "auto", **kwargs):
    """Dispatch between the XLA reference and the Pallas flash kernel.

    impl='auto' picks per shape (resolve_impl); 'flash' / 'xla' force the
    respective path.
    """
    if not (kwargs.get("attn_dropout", 0.0) > 0.0
            and kwargs.get("attn_dropout_rng") is not None):
        kwargs.pop("attn_dropout", None)
        kwargs.pop("attn_dropout_rng", None)
    # (train-mode probs dropout is supported by BOTH impls: the flash
    # kernels generate the mask in-kernel from a counter-based hash —
    # see flash_attention.py _keep_mask — so dropout no longer forces the
    # XLA path)
    if impl == "auto":
        impl = resolve_impl(q.shape[2], q.shape[3])
    if impl == "flash":
        try:
            from mobilefinetuner_tpu.ops import flash_attention
        except ImportError as e:
            raise NotImplementedError(
                "attention_impl='flash' requires the Pallas kernel "
                "(ops/flash_attention.py); use attention_impl='xla'") from e
        return flash_attention.flash_attention(q, k, v, **kwargs)
    # backward-impl selection is a flash-kernel knob; the XLA path has
    # one backward (jax autodiff)
    kwargs.pop("bwd_impl", None)
    return dot_product_attention(q, k, v, **kwargs)
