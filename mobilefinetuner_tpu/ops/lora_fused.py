"""Pallas LoRA epilogue: fold the adapter delta into the projection's
output pass so it never round-trips HBM.

The XLA spelling of a LoRA site is

    y = x @ W            (the base projection)
    y = y + s * (xa @ B)  with  xa = x @ A   (rank-r bottleneck)

A Note on LoRA (PAPERS.md) observes the delta is MEMORY-bound: at rank
r ≪ d the second matmul does ~2·N·r·d_out FLOPs but XLA materializes the
[N, d_out] delta and re-reads y to add it — two extra HBM round-trips of
a y-sized tensor for a matmul the MXU finishes in a corner of one tile
pass. This kernel computes `y + xa @ B` in ONE tiled pass over y: per
(row-block, col-block) grid step it reads the y tile once, adds the
rank-r product computed in VMEM with f32 accumulation, and writes the
result. xa arrives pre-scaled (scale is folded outside, where its
stop_gradient lives — models/lora_apply.py).

Alignment: the rank dim (8..64 in practice) is far below the 128-lane
tile, so the wrapper zero-pads xa/B to R_PAD=128 lanes — 16x pad FLOPs
on a matmul that is ~r/d of the site's work, i.e. noise, in exchange for
clean tiling on every jax version. The custom_vjp backward is plain XLA
(dy = g passthrough; dxa = g @ Bᵀ; dB = xaᵀ @ g, all f32-accumulated):
the backward has no y-sized temp to eliminate, so a kernel would only
add launch overhead there.

Eligibility (lora_epilogue_eligible): rows sublane-aligned (N % 8),
lanes tile-aligned (d_out % 128), r ≤ R_PAD, and a (bn, bd) tile pair
within the VMEM budget. Ineligible sites fall back to the XLA order in
maybe_lora — same numerics (tests/test_lora_fused.py pins parity and
grads against the naive oracle, interpret mode on CPU).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mobilefinetuner_tpu.ops.pallas_util import tpu_call_params

R_PAD = 128                  # rank dim padded to one lane tile
_VMEM_BUDGET = 14 * 2 ** 20  # headroom under the 16 MB scoped limit


def pick_tiles(N: int, d_out: int,
               itemsize: int = 2) -> Optional[Tuple[int, int]]:
    """Largest (row, col) tile pair dividing [N, d_out] that fits the
    VMEM budget (None = ineligible). Resident per grid step: the y and
    out tiles (double-buffered), the [bn, R_PAD] xa slab, the
    [R_PAD, bd] B slab (double-buffered), and the f32 accumulator."""
    for bn in (512, 256, 128, 64, 32, 16, 8):
        if N % bn:
            continue
        for bd in (512, 256, 128):
            if d_out % bd:
                continue
            need = (2 * 2 * bn * bd * itemsize      # y in + out, buffered
                    + 2 * bn * R_PAD * itemsize     # xa slab
                    + 2 * R_PAD * bd * itemsize     # B slab
                    + bn * bd * 4)                  # f32 accumulator
            if need <= _VMEM_BUDGET:
                return bn, bd
    return None


def lora_epilogue_eligible(N: int, d_out: int, r: int,
                           itemsize: int = 2) -> bool:
    """Shape gate consulted by maybe_lora and resolve_lora_impl."""
    return (N % 8 == 0 and d_out % 128 == 0 and 0 < r <= R_PAD
            and pick_tiles(N, d_out, itemsize) is not None)


def _epilogue_kernel(y_ref, xa_ref, b_ref, o_ref):
    acc = jax.lax.dot_general(
        xa_ref[:], b_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)        # [bn, bd] f32
    o_ref[:] = (y_ref[:].astype(jnp.float32) + acc).astype(o_ref.dtype)


def _call(y2, xa2, b2):
    N, d_out = y2.shape
    tiles = pick_tiles(N, d_out, y2.dtype.itemsize)
    if tiles is None:
        raise ValueError(
            f"lora epilogue ineligible for N={N}, d_out={d_out} (check "
            f"lora_epilogue_eligible before calling)")
    bn, bd = tiles
    call = pl.pallas_call(
        _epilogue_kernel,
        grid=(N // bn, d_out // bd),
        in_specs=[
            pl.BlockSpec((bn, bd), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, R_PAD), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R_PAD, bd), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, bd), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, d_out), y2.dtype),
        **tpu_call_params("parallel", "parallel"),
    )
    with jax.named_scope("lora_epilogue"):
        return call(y2, xa2, b2)


@jax.custom_vjp
def _epilogue2(y2, xa2, b2):
    """y2 + xa2 @ b2 over padded 2-D operands (xa2 [N, R_PAD] already
    scale-folded, b2 [R_PAD, d_out]). The pad/scale plumbing lives in
    lora_epilogue_add so its transposes come from plain XLA autodiff."""
    return _call(y2, xa2, b2)


def _vjp_fwd(y2, xa2, b2):
    return _call(y2, xa2, b2), (xa2, b2)


def _vjp_bwd(res, g):
    xa2, b2 = res
    gf = g
    dxa = jax.lax.dot_general(
        gf, b2, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(xa2.dtype)
    db = jax.lax.dot_general(
        xa2, gf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(b2.dtype)
    return g, dxa, db


_epilogue2.defvjp(_vjp_fwd, _vjp_bwd)


def lora_epilogue_add(y, xa, B, scale):
    """y + scale·(xa @ B) through the fused tile pass.

    y [..., d_out] (any leading shape), xa [..., r] the rank-r
    bottleneck in the compute dtype, B [r, d_out], scale a (stop-
    gradiented) f32 scalar. Returns y's shape and dtype."""
    d_out = y.shape[-1]
    r = xa.shape[-1]
    N = y.size // d_out
    xs = (xa.astype(jnp.float32) * scale).astype(y.dtype)
    xa2 = jnp.pad(xs.reshape(N, r), ((0, 0), (0, R_PAD - r)))
    b2 = jnp.pad(B.astype(y.dtype), ((0, R_PAD - r), (0, 0)))
    out = _epilogue2(y.reshape(N, d_out), xa2, b2)
    return out.reshape(y.shape)
