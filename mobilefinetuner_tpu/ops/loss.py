"""Language-model cross-entropy loss.

Semantics mirror the reference's `lm_cross_entropy`
(reference: operators/finetune_ops/core/lm_loss.cpp:19-103):
  - the HF label shift is performed INTERNALLY (logits[:, :-1] vs
    labels[:, 1:], lm_loss.cpp:27-32) — callers pass UNSHIFTED labels and
    must not shift again (SURVEY.md §2.12.4);
  - ignore_index = -100 positions contribute nothing and are excluded from
    the valid-token count;
  - "mean" reduction divides by the number of valid (non-ignored) tokens;
  - numerically stable logsumexp in fp32 regardless of logits dtype.

The backward is JAX autodiff of this forward — analytically identical to the
reference's fused `(softmax - onehot)/valid_count` (lm_loss.cpp:105+).

`chunked_lm_cross_entropy` fuses the lm_head projection with the loss over
sequence chunks so the full [B,S,V] logits tensor is never materialized —
needed for Gemma-3's 262k vocab (SURVEY.md §7 hard part (d)).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def _shift(logits: jnp.ndarray, labels: jnp.ndarray):
    return logits[:, :-1, :], labels[:, 1:]


def _token_nll(logits: jnp.ndarray, labels: jnp.ndarray,
               ignore_index: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token NLL (fp32) and validity mask. No shift here."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, safe_labels[..., None], axis=-1).squeeze(-1)
    nll = jnp.where(valid, lse - gold, 0.0)
    return nll, valid


def lm_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                     ignore_index: int = IGNORE_INDEX,
                     reduction: str = "mean") -> jnp.ndarray:
    """Causal-LM loss over UNSHIFTED labels; shift happens inside.

    logits: [B, S, V] (any float dtype), labels: [B, S] int.
    Returns scalar for "mean"/"sum", [B, S-1] for "none".
    """
    logits_s, labels_s = _shift(logits, labels)
    nll, valid = _token_nll(logits_s, labels_s, ignore_index)
    if reduction == "none":
        return nll
    total = nll.sum()
    if reduction == "sum":
        return total
    count = jnp.maximum(valid.sum(), 1)
    return total / count


def lm_cross_entropy_sum(
        logits: jnp.ndarray, labels: jnp.ndarray,
        ignore_index: int = IGNORE_INDEX) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum_nll, valid_token_count) — the accumulation-friendly form used by
    the train step for exact token-weighted gradient accumulation."""
    logits_s, labels_s = _shift(logits, labels)
    nll, valid = _token_nll(logits_s, labels_s, ignore_index)
    return nll.sum(), valid.sum()


def lm_cross_entropy_with_count(
        logits: jnp.ndarray, labels: jnp.ndarray,
        ignore_index: int = IGNORE_INDEX) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(mean_loss, valid_token_count) — eval_ppl needs token-weighted
    accumulation (reference: gpt2_lora_finetune/eval_ppl.cpp:157-200)."""
    logits_s, labels_s = _shift(logits, labels)
    nll, valid = _token_nll(logits_s, labels_s, ignore_index)
    count = valid.sum()
    return nll.sum() / jnp.maximum(count, 1), count


@partial(jax.jit, static_argnames=("ignore_index", "num_chunks"))
def _chunked_nll_sum(hidden, lm_head_w, labels, ignore_index, num_chunks):
    B, S, H = hidden.shape
    # Head matmul in the COMPUTE dtype with f32 accumulation: casting both
    # operands to f32 (the old form) forces the multi-pass f32 MXU
    # lowering on the [chunk, H] x [H, 262k] projection — the dominant
    # matmul of the small-Gemma configs. Under the bf16 compute policy the
    # hidden states arrive bf16; aligning the (frozen, tied) head weight
    # to them keeps the projection a single bf16 MXU pass, while
    # preferred_element_type=f32 in the dot and the f32 logsumexp in
    # _token_nll keep the reduction math exact. f32 callers (parity tests,
    # --dtype float32) are bit-for-bit unchanged.
    if jnp.issubdtype(hidden.dtype, jnp.floating):
        lm_head_w = lm_head_w.astype(hidden.dtype)
    # Shift first: positions 0..S-2 predict labels 1..S-1.
    hidden_s = hidden[:, :-1, :]
    labels_s = labels[:, 1:]
    # Pad S-1 up to a multiple of num_chunks with ignored positions.
    Sm1 = S - 1
    pad = (-Sm1) % num_chunks
    if pad:
        hidden_s = jnp.pad(hidden_s, ((0, 0), (0, pad), (0, 0)))
        labels_s = jnp.pad(labels_s, ((0, 0), (0, pad)),
                           constant_values=ignore_index)
    chunk = (Sm1 + pad) // num_chunks
    hs = hidden_s.reshape(B, num_chunks, chunk, H).swapaxes(0, 1)
    ls = labels_s.reshape(B, num_chunks, chunk).swapaxes(0, 1)

    def body(carry, xs):
        total, count = carry
        h, lab = xs
        logits = jax.lax.dot_general(
            h, lm_head_w, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [B, chunk, V] f32
        nll, valid = _token_nll(logits, lab, ignore_index)
        return (total + nll.sum(), count + valid.sum()), None

    (total, count), _ = jax.lax.scan(
        jax.checkpoint(body), (jnp.float32(0.0), jnp.int32(0)), (hs, ls))
    return total, count


def chunked_lm_cross_entropy(hidden: jnp.ndarray, lm_head_w: jnp.ndarray,
                             labels: jnp.ndarray,
                             ignore_index: int = IGNORE_INDEX,
                             num_chunks: int = 8) -> jnp.ndarray:
    """Mean causal-LM loss computed without materializing [B,S,V] logits.

    hidden: [B, S, H] final hidden states; lm_head_w: [V, H] (HF layout);
    labels: [B, S] unshifted. The projection + logsumexp runs per sequence
    chunk under lax.scan with rematerialization, so peak memory holds one
    [B, S/num_chunks, V] block. Differentiable end-to-end.
    """
    total, count = _chunked_nll_sum(hidden, lm_head_w, labels,
                                    ignore_index, num_chunks)
    return total / jnp.maximum(count, 1).astype(jnp.float32)


def chunked_lm_cross_entropy_sum(
        hidden: jnp.ndarray, lm_head_w: jnp.ndarray, labels: jnp.ndarray,
        ignore_index: int = IGNORE_INDEX,
        num_chunks: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum_nll, valid_token_count) form of the chunked loss — the
    accumulation-friendly contract the train step uses (trainer.py)."""
    return _chunked_nll_sum(hidden, lm_head_w, labels, ignore_index,
                            num_chunks)


def perplexity_from_loss(loss) -> float:
    """ppl = exp(mean NLL) (reference: core/lm_loss.h:39-41)."""
    import math
    return math.exp(float(loss))
