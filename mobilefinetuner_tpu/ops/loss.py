"""Language-model cross-entropy loss.

Semantics mirror the reference's `lm_cross_entropy`
(reference: operators/finetune_ops/core/lm_loss.cpp:19-103):
  - the HF label shift is performed INTERNALLY (logits[:, :-1] vs
    labels[:, 1:], lm_loss.cpp:27-32) — callers pass UNSHIFTED labels and
    must not shift again (SURVEY.md §2.12.4);
  - ignore_index = -100 positions contribute nothing and are excluded from
    the valid-token count;
  - "mean" reduction divides by the number of valid (non-ignored) tokens;
  - numerically stable logsumexp in fp32 regardless of logits dtype.

The backward is JAX autodiff of this forward — analytically identical to the
reference's fused `(softmax - onehot)/valid_count` (lm_loss.cpp:105+).

`chunked_lm_cross_entropy` fuses the lm_head projection with the loss over
sequence chunks so the full [B,S,V] logits tensor is never materialized —
needed for Gemma-3's 262k vocab (SURVEY.md §7 hard part (d)).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100


def _shift(logits: jnp.ndarray, labels: jnp.ndarray):
    return logits[:, :-1, :], labels[:, 1:]


def _token_nll(logits: jnp.ndarray, labels: jnp.ndarray,
               ignore_index: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token NLL (fp32) and validity mask. No shift here."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, safe_labels[..., None], axis=-1).squeeze(-1)
    nll = jnp.where(valid, lse - gold, 0.0)
    return nll, valid


def lm_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                     ignore_index: int = IGNORE_INDEX,
                     reduction: str = "mean") -> jnp.ndarray:
    """Causal-LM loss over UNSHIFTED labels; shift happens inside.

    logits: [B, S, V] (any float dtype), labels: [B, S] int.
    Returns scalar for "mean"/"sum", [B, S-1] for "none".
    """
    with jax.named_scope("loss"):
        logits_s, labels_s = _shift(logits, labels)
        nll, valid = _token_nll(logits_s, labels_s, ignore_index)
        if reduction == "none":
            return nll
        total = nll.sum()
        if reduction == "sum":
            return total
        count = jnp.maximum(valid.sum(), 1)
        return total / count


def lm_cross_entropy_sum(
        logits: jnp.ndarray, labels: jnp.ndarray,
        ignore_index: int = IGNORE_INDEX) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum_nll, valid_token_count) — the accumulation-friendly form used by
    the train step for exact token-weighted gradient accumulation."""
    with jax.named_scope("loss"):
        logits_s, labels_s = _shift(logits, labels)
        nll, valid = _token_nll(logits_s, labels_s, ignore_index)
        return nll.sum(), valid.sum()


def lm_cross_entropy_rows(
        logits: jnp.ndarray, labels: jnp.ndarray,
        ignore_index: int = IGNORE_INDEX) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-ROW (sum_nll [B], valid_token_count [B]) — the multi-tenant
    train step's form (train/trainer.make_multi_train_step): each batch
    row belongs to one adapter job, so the step segment-sums these row
    vectors by adapter id and normalizes each tenant's gradient by its
    OWN token count (summing first and normalizing jointly would couple
    every tenant's update to the others' token counts). Summing the two
    vectors recovers lm_cross_entropy_sum exactly."""
    with jax.named_scope("loss"):
        logits_s, labels_s = _shift(logits, labels)
        nll, valid = _token_nll(logits_s, labels_s, ignore_index)
        return nll.sum(axis=-1), valid.sum(axis=-1)


def lm_cross_entropy_with_count(
        logits: jnp.ndarray, labels: jnp.ndarray,
        ignore_index: int = IGNORE_INDEX) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(mean_loss, valid_token_count) — eval_ppl needs token-weighted
    accumulation (reference: gpt2_lora_finetune/eval_ppl.cpp:157-200)."""
    with jax.named_scope("loss"):
        logits_s, labels_s = _shift(logits, labels)
        nll, valid = _token_nll(logits_s, labels_s, ignore_index)
        count = valid.sum()
        return nll.sum() / jnp.maximum(count, 1), count


def chunk_len(S: int, num_chunks: int) -> int:
    """Per-chunk length _shift_and_chunk produces for a [B, S, H] input —
    THE one copy of the pad arithmetic (the SP eligibility gate and the
    dryrun's phase guard both depend on it staying in lockstep)."""
    Sm1 = S - 1
    return (Sm1 + ((-Sm1) % num_chunks)) // num_chunks


def _shift_and_chunk(hidden, labels, ignore_index, num_chunks):
    """Shared shift/pad/chunk front end: [B,S,H] -> [num_chunks,B,chunk,H]
    (positions 0..S-2 predict labels 1..S-1; the pad tail is ignored)."""
    B, S, H = hidden.shape
    hidden_s = hidden[:, :-1, :]
    labels_s = labels[:, 1:]
    pad = num_chunks * chunk_len(S, num_chunks) - (S - 1)
    if pad:
        hidden_s = jnp.pad(hidden_s, ((0, 0), (0, pad), (0, 0)))
        labels_s = jnp.pad(labels_s, ((0, 0), (0, pad)),
                           constant_values=ignore_index)
    chunk = chunk_len(S, num_chunks)
    hs = hidden_s.reshape(B, num_chunks, chunk, H).swapaxes(0, 1)
    ls = labels_s.reshape(B, num_chunks, chunk).swapaxes(0, 1)
    return hs, ls


def _vp_chunked_nll_sum(hidden, lm_head_w, labels, ignore_index, num_chunks,
                        mesh, batch_axis, vocab_axis, seq_shard=False):
    """Vocab-parallel chunked CE under shard_map — the multi-device path.

    The fsdp-sharded [V, H] head table must NOT be all-gathered per step:
    without explicit structure GSPMD picks exactly that (gather the table,
    keep B fully sharded), which for Gemma's 262k vocab re-materializes
    the full table in HBM each step and defeats FSDP — and
    with_sharding_constraint on the logits alone is not enough (the
    partitioner's cost model still gathers the table at large mesh
    sizes). shard_map makes the Megatron-style algorithm structural:
    each device holds its [V/n, H] shard, computes its logits slice
    [B/data, chunk, V/n], and the softmax statistics reduce over the
    vocab axis with three tiny psums per chunk (max, sum-exp, gold
    logit). Per-device FLOPs equal the batch-sharded layout; the only
    resharding is a small hidden all-gather over the vocab axis.
    Gradients flow through dot/psum/take_along_axis (pmax is wrapped in
    stop_gradient — the lse value is invariant to the max shift, so the
    softmax gradient is exact). tests/test_multichip.py asserts the
    compiled HLO carries no full-table all-gather.

    seq_shard=True is the SEQUENCE-PARALLEL composition (round-5 verdict
    item 2): under ring attention the vocab axis ("fsdp") carries the
    sequence, so the incoming chunk dim arrives sharded over that same
    axis. Each scan step then all-gathers its [B/data, chunk/n, H] hidden
    slice over the axis (tiny — hidden bytes, the Megatron gather-at-head
    move) and proceeds exactly as above: the table stays [V/n, H]-sharded
    and the per-device logits block stays [B/data, chunk, V/n]. The
    gather's transpose is a reduce-scatter of dH back to each device's
    own sequence slice, so the backward keeps the sequence sharded too.
    """
    from jax.sharding import PartitionSpec as P
    from mobilefinetuner_tpu.core.compat import shard_map

    if jnp.issubdtype(hidden.dtype, jnp.floating):
        lm_head_w = lm_head_w.astype(hidden.dtype)
    hs, ls = _shift_and_chunk(hidden, labels, ignore_index, num_chunks)

    def local(hs, ls, w):
        vloc = w.shape[0]
        start = jax.lax.axis_index(vocab_axis) * vloc

        def body(carry, xs):
            total, count = carry
            h, lab = xs
            if seq_shard:
                # reassemble the full chunk from the sequence shards; lab
                # enters unsharded on this axis (tiny int array)
                h = jax.lax.all_gather(h, vocab_axis, axis=1, tiled=True)
            logits = jax.lax.dot_general(
                h, w, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)  # [B_loc, chunk, V/n]
            valid = lab != ignore_index
            # global max via all_gather (pmax has no differentiation rule
            # even under an outer stop_gradient — tracing is inside-out);
            # the gathered tensor is a tiny [n, B_loc, chunk]
            m = jax.lax.stop_gradient(
                jax.lax.all_gather(logits.max(-1), vocab_axis).max(0))
            se = jnp.sum(jnp.exp(logits - m[..., None]), -1)
            lse = jnp.log(jax.lax.psum(se, vocab_axis)) + m
            loc = lab - start
            in_shard = valid & (loc >= 0) & (loc < vloc)
            safe = jnp.clip(loc, 0, vloc - 1)
            gold_loc = jnp.take_along_axis(
                logits, safe[..., None], axis=-1)[..., 0]
            gold = jnp.where(in_shard, gold_loc, 0.0)
            gold = jax.lax.psum(gold, vocab_axis)
            nll = jnp.where(valid, lse - gold, 0.0)
            return (total + nll.sum(), count + valid.sum()), None

        (total, count), _ = jax.lax.scan(
            jax.checkpoint(body), (jnp.float32(0.0), jnp.int32(0)),
            (hs, ls))
        # batch is sharded over batch_axis only (vocab-axis members hold
        # identical replicas after the psums above)
        return (jax.lax.psum(total, batch_axis),
                jax.lax.psum(count, batch_axis))

    chunk_spec = vocab_axis if seq_shard else None
    return shard_map(
        local, mesh=mesh,
        in_specs=(P(None, batch_axis, chunk_spec, None),
                  P(None, batch_axis, None), P(vocab_axis, None)),
        out_specs=(P(), P()), check_vma=False)(hs, ls, lm_head_w)


def vp_embed_lookup(table, ids, mesh, *, vocab_axis: str = "fsdp",
                    batch_axis: str = "data"):
    """Sequence-parallel vocab-parallel embedding LOOKUP: the Megatron
    front-end companion of _vp_chunked_nll_sum's head.

    Under --sequence_parallel one mesh axis carries BOTH the sequence
    shard of the activations and the vocab shard of the tied [V, H]
    table. Left to itself, GSPMD's cost model resolves `table[ids]` by
    ALL-GATHERING THE TABLE at large mesh sizes (observed at fsdp >= 16
    in the pod dryrun; at fsdp=4 it happens to pick the sharded plan) —
    re-materializing the 262k-row table per step, exactly the failure
    the vocab-parallel CE exists to prevent. shard_map makes the sharded
    plan structural: each device all-gathers the TINY int ids over the
    axis, looks the full sequence up against its OWN table shard
    (out-of-shard rows contribute zero), and the partial embeddings
    psum_scatter straight back to the sequence shard — one [B, S, H]
    reduce-scatter, the same bytes the SP activations already move, and
    the full table never exists. Differentiable end to end: the
    psum_scatter's transpose is an all-gather and the masked take's is a
    scatter-add into the local shard, so the trainable tied embed (full
    FT) gets exact vocab-sharded gradients.

    ids: [B, S] int, sequence-sharded over `vocab_axis` (batch over
    `batch_axis` when present); table: [V, H] V-sharded. V and S must
    divide by the axis size (the caller gates). Returns [B, S, H] in the
    table's dtype, sharded like the SP activations."""
    from jax.sharding import PartitionSpec as P
    from mobilefinetuner_tpu.core.compat import shard_map
    ba = batch_axis if batch_axis in mesh.axis_names else None

    def local(w, ids_loc):
        vloc = w.shape[0]
        start = jax.lax.axis_index(vocab_axis) * vloc
        ids_full = jax.lax.all_gather(ids_loc, vocab_axis, axis=1,
                                      tiled=True)          # [B_loc, S]
        loc = ids_full - start
        in_shard = (loc >= 0) & (loc < vloc)
        safe = jnp.clip(loc, 0, vloc - 1)
        e = jnp.take(w, safe, axis=0)                      # [B_loc, S, H]
        e = jnp.where(in_shard[..., None], e, 0)
        return jax.lax.psum_scatter(e, vocab_axis, scatter_dimension=1,
                                    tiled=True)            # [B_loc, S/n, H]

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(vocab_axis, None), P(ba, vocab_axis)),
        out_specs=P(ba, vocab_axis, None), check_vma=False)(table, ids)


def _use_fused_ce(use_fused_kernel, R, V, H, itemsize=2, lora_r=0,
                  lora_impl="naive") -> bool:
    """Resolve the fused-head-kernel dispatch. "auto" currently resolves
    to the XLA path on every shape: measured on v5e (r4), the Pallas
    fused head (ops/fused_ce.py) is ~6% SLOWER than XLA's consumer-fused
    matmul+logsumexp at Gemma-270M shapes and exactly at parity at
    Gemma-1B — XLA already keeps the chunk logits out of HBM well enough
    that the kernel's per-tile overhead has nothing to win back
    (DESIGN.md §5a). True forces the kernel (tests; future re-measure
    when the compiler or shapes change).

    lora_r > 0 is the head-ADAPTER case (DESIGN.md §17): under
    lora_impl="fused" the kernel engages whenever the epilogue variant
    is eligible (the adapter delta is the HBM traffic the base kernel
    never had to win back); "auto"/"naive" keep the XLA chunk path
    pending a TPU measurement."""
    from mobilefinetuner_tpu.ops.fused_ce import (fused_ce_eligible,
                                                  fused_ce_lora_eligible)
    eligible = (fused_ce_lora_eligible(R, V, H, lora_r, itemsize)
                if lora_r else fused_ce_eligible(R, V, H, itemsize))
    if use_fused_kernel == "auto":
        return bool(lora_r) and lora_impl == "fused" and eligible
    if not use_fused_kernel:
        return False
    if not eligible:
        # forcing must be loud: a silent XLA fallback would let a future
        # re-measure record XLA numbers as kernel numbers
        raise ValueError(
            f"use_fused_kernel=True but the fused CE kernel cannot run "
            f"R={R}, V={V}, H={H}, lora_r={lora_r} (alignment or VMEM "
            f"budget — fused_ce.pick_block_v); use 'auto' for dispatch")
    return True


@partial(jax.jit, static_argnames=("ignore_index", "num_chunks", "mesh",
                                   "batch_axis", "vocab_axis",
                                   "use_fused_kernel", "sequence_parallel",
                                   "lora_impl", "lora_dropout"))
def _chunked_nll_sum(hidden, lm_head_w, labels, ignore_index, num_chunks,
                     mesh=None, batch_axis="data", vocab_axis="fsdp",
                     use_fused_kernel="auto", sequence_parallel=False,
                     lora_head=None, lora_impl="naive",
                     lora_dropout=0.0, dropout_rng=None):
    if mesh is not None:
        V = lm_head_w.shape[0]
        B, S = hidden.shape[0], hidden.shape[1]
        n_vocab = mesh.shape.get(vocab_axis, 1)
        n_batch = mesh.shape.get(batch_axis, 1)
        # sequence-parallel composition: the chunk dim arrives sharded
        # over the vocab axis, so each scan chunk must split evenly
        # across it (see _vp_chunked_nll_sum seq_shard)
        chunk = chunk_len(S, num_chunks)
        sp_ok = (not sequence_parallel) or chunk % n_vocab == 0
        if n_vocab > 1 and V % n_vocab == 0 and B % n_batch == 0 and sp_ok:
            if use_fused_kernel is True:
                raise ValueError(
                    "use_fused_kernel=True is not available under the "
                    "vocab-parallel mesh path (shard_map CE)")
            if lora_head is not None:
                # a head adapter under the vocab-parallel CE would need
                # B column-sharded inside the shard_map — not built this
                # round; refusing beats silently dropping the delta
                raise ValueError(
                    "lora_head (lm_head adapter) is not supported under "
                    "the vocab-parallel CE path; run with mesh=None or "
                    "drop the lm_head target")
            return _vp_chunked_nll_sum(hidden, lm_head_w, labels,
                                       ignore_index, num_chunks, mesh,
                                       batch_axis, vocab_axis,
                                       seq_shard=sequence_parallel)
        if n_vocab > 1:
            # the caller asked for vocab-parallel but the shapes can't
            # shard — warn (once per trace: shapes are static) instead of
            # silently reintroducing the full-table all-gather/OOM this
            # path exists to prevent
            import warnings
            warnings.warn(
                f"vocab-parallel CE requested but V={V} % {vocab_axis}="
                f"{n_vocab} != 0 or B={B} % {batch_axis}={n_batch} != 0"
                + ("" if sp_ok else
                   f" or sequence-parallel chunk={chunk} % {n_vocab} != 0")
                + "; falling back to the single-program chunked CE (GSPMD "
                f"may all-gather the full [V, H] head table per step)",
                stacklevel=2)
    # Head matmul in the COMPUTE dtype with f32 accumulation: casting both
    # operands to f32 (the old form) forces the multi-pass f32 MXU
    # lowering on the [chunk, H] x [H, 262k] projection — the dominant
    # matmul of the small-Gemma configs. Under the bf16 compute policy the
    # hidden states arrive bf16; aligning the (frozen, tied) head weight
    # to them keeps the projection a single bf16 MXU pass, while
    # preferred_element_type=f32 in the dot and the f32 logsumexp in
    # _token_nll keep the reduction math exact. f32 callers (parity tests,
    # --dtype float32) are bit-for-bit unchanged.
    if jnp.issubdtype(hidden.dtype, jnp.floating):
        lm_head_w = lm_head_w.astype(hidden.dtype)
    hs, ls = _shift_and_chunk(hidden, labels, ignore_index, num_chunks)
    nc, B, chunk, H = hs.shape
    # Train-mode LoRA dropout on the head adapter's branch input (PEFT
    # semantics: the branch copy only, never the base logits). Masked
    # over the FULL hidden with the same fold_in(rng, 2000) site key as
    # the models' full-logits lm_head sites (gpt2/gemma3.forward), then
    # chunked alongside — bit-identical branch input to the unchunked
    # path, uncorrelated with every per-layer site mask.
    hbs = None
    if (lora_head is not None and lora_dropout > 0.0
            and dropout_rng is not None):
        from mobilefinetuner_tpu.ops.dropout import inverted_dropout
        dropped = inverted_dropout(
            hidden, lora_dropout, jax.random.fold_in(dropout_rng, 2000))
        hbs, _ = _shift_and_chunk(dropped, labels, ignore_index,
                                  num_chunks)
    lora_r = 0 if lora_head is None else int(lora_head["A"].shape[-1])

    # xs only grows the branch-hidden leaf when dropout is live — the
    # base graph (and every no-dropout caller's trace) is unchanged
    def unpack(xs):
        if hbs is None:
            h, lab = xs
            return h, h, lab
        return xs

    if _use_fused_ce(use_fused_kernel, B * chunk, lm_head_w.shape[0], H,
                     lm_head_w.dtype.itemsize, lora_r=lora_r,
                     lora_impl=lora_impl):
        # Pallas fused head (ops/fused_ce.py): the [B, chunk, V] logits
        # block stays in VMEM tiles instead of being written + twice-read
        # in HBM per chunk (and again in the checkpointed backward) —
        # with a head adapter, its delta folds into the same tile loop
        from mobilefinetuner_tpu.ops.fused_ce import fused_ce_nll_sum

        def body(carry, xs):
            total, count = carry
            h, hb, lab = unpack(xs)
            s, c = fused_ce_nll_sum(h, lm_head_w, lab, ignore_index,
                                    lora_head=lora_head,
                                    branch_hidden=hb)
            return (total + s, count + c), None
    else:
        def body(carry, xs):
            total, count = carry
            h, hb, lab = unpack(xs)
            logits = jax.lax.dot_general(
                h, lm_head_w, (((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)    # [B, chunk, V] f32
            if lora_head is not None:
                # chunk-local head-adapter delta (the contraction-order
                # rule: (h@A)@B, f32-accumulated; scale-folding shared
                # with the kernel path via head_bottleneck) — only a
                # [B, chunk, V] block ever exists, like the base logits
                from mobilefinetuner_tpu.ops.fused_ce import \
                    head_bottleneck
                xa, bt = head_bottleneck(hb.reshape(B * chunk, H),
                                         lora_head)
                logits = logits + jnp.einsum(
                    "rk,vk->rv", xa, bt,
                    preferred_element_type=jnp.float32) \
                    .reshape(B, chunk, -1)
            nll, valid = _token_nll(logits, lab, ignore_index)
            return (total + nll.sum(), count + valid.sum()), None

    (total, count), _ = jax.lax.scan(
        jax.checkpoint(body), (jnp.float32(0.0), jnp.int32(0)),
        (hs, ls) if hbs is None else (hs, hbs, ls))
    return total, count


def chunked_lm_cross_entropy(hidden: jnp.ndarray, lm_head_w: jnp.ndarray,
                             labels: jnp.ndarray,
                             ignore_index: int = IGNORE_INDEX,
                             num_chunks: int = 8, mesh=None,
                             batch_axis: str = "data",
                             vocab_axis: str = "fsdp",
                             use_fused_kernel="auto",
                             sequence_parallel: bool = False,
                             lora_head=None,
                             lora_impl: str = "naive",
                             lora_dropout: float = 0.0,
                             dropout_rng=None) -> jnp.ndarray:
    """Mean causal-LM loss computed without materializing [B,S,V] logits.

    hidden: [B, S, H] final hidden states; lm_head_w: [V, H] (HF layout);
    labels: [B, S] unshifted. The projection + logsumexp runs per sequence
    chunk under lax.scan with rematerialization, so peak memory holds one
    [B, S/num_chunks, V] block. Differentiable end-to-end.

    mesh: pass the ("data", "fsdp") device mesh when lm_head_w is
    FSDP-sharded to run the CE vocab-parallel (table stays sharded; see
    _chunked_nll_sum). In sequence-parallel mode (ring attention, the
    fsdp axis carrying S) ALSO pass sequence_parallel=True: the CE then
    gathers each hidden chunk over that axis before the vocab-parallel
    softmax, so the long-context configuration keeps the no-table-gather
    guarantee (round-5 verdict item 2).

    lora_head: optional lm_head adapter entry {A [H, r], B [r, V],
    scale}; its delta is applied chunk-locally (XLA) or folded into the
    fused kernel's tile loop (lora_impl="fused" when eligible) — the
    full [B, S, V] delta never materializes either way (DESIGN.md §17).
    lora_dropout/dropout_rng: train-mode inverted dropout on the head
    adapter's branch input (PEFT semantics, same fold_in(rng, 2000)
    site key as the models' full-logits lm_head sites) — pass the train
    CLI's --lora_dropout and per-micro-batch rng so the lm_head target
    regularizes like every per-layer site.
    """
    with jax.named_scope("loss"):
        total, count = _chunked_nll_sum(hidden, lm_head_w, labels,
                                        ignore_index, num_chunks, mesh,
                                        batch_axis, vocab_axis,
                                        use_fused_kernel,
                                        sequence_parallel, lora_head,
                                        lora_impl, lora_dropout,
                                        dropout_rng)
        return total / jnp.maximum(count, 1).astype(jnp.float32)


def chunked_lm_cross_entropy_sum(
        hidden: jnp.ndarray, lm_head_w: jnp.ndarray, labels: jnp.ndarray,
        ignore_index: int = IGNORE_INDEX, num_chunks: int = 8, mesh=None,
        batch_axis: str = "data", vocab_axis: str = "fsdp",
        use_fused_kernel="auto", sequence_parallel: bool = False,
        lora_head=None, lora_impl: str = "naive",
        lora_dropout: float = 0.0,
        dropout_rng=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(sum_nll, valid_token_count) form of the chunked loss — the
    accumulation-friendly contract the train step uses (trainer.py).
    mesh/sequence_parallel/lora_head/lora_dropout: see
    chunked_lm_cross_entropy."""
    with jax.named_scope("loss"):
        return _chunked_nll_sum(hidden, lm_head_w, labels, ignore_index,
                                num_chunks, mesh, batch_axis, vocab_axis,
                                use_fused_kernel, sequence_parallel,
                                lora_head, lora_impl, lora_dropout,
                                dropout_rng)


def perplexity_from_loss(loss) -> float:
    """ppl = exp(mean NLL) (reference: core/lm_loss.h:39-41)."""
    import math
    # graftlint: disable=sync-hazard(eval-end conversion: callers hand a host scalar or accept the one post-loop sync)
    return math.exp(float(loss))
