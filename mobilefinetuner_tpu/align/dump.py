"""Alignment-dump harness: npy snapshots of one training step for
comparison against a PyTorch/PEFT mirror.

Rebuild of the reference's align mode
(reference: operators/finetune_ops/optim/train_lora_gemma.cpp:620-920 —
single-batch forward/backward dumping activations, per-layer grads, and
post-step weights as .npy; plus graph/save_pt_gold.py and the
pytorch_alignment/ mirror scripts). The dump side is framework-native
(this module, wired to the train CLIs via --align_dump_dir); the torch
side is tools/align_torch_mirror.py, which loads the same checkpoint +
batch, recomputes every tensor with HF transformers + PEFT, and reports
max abs/rel errors.

Dump layout (all .npy unless noted):
  batch_input_ids, batch_attention_mask, batch_labels
  act_embed            [B, S, E]   post-embedding activations
  act_layer_{i:02d}    [B, S, E]   post-block activations, per layer
  logits               [B, S, V]
  loss                 []          mean CE over valid tokens (HF semantics)
  losses               [N]         loss per step over N steps on the batch
  grads/{dotted}.npy               d(loss)/d(adapter), our key scheme
  adapter_pre/{dotted}.npy         adapter before the first step
  adapter_post/{dotted}.npy        adapter after ONE optimizer step
  peft/                            HF-PEFT export of adapter_pre (the
                                   mirror loads this to start identical)
  meta.json                        hparams the mirror needs

Align runs force a CONSTANT learning rate (no warmup/decay) so the mirror
only needs torch.optim.AdamW with the same lr — schedule parity is covered
by the optimizer unit tests instead.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from mobilefinetuner_tpu.core.logging import get_logger
from mobilefinetuner_tpu.lora import peft_io
from mobilefinetuner_tpu.train.trainer import (TrainConfig, init_optimizer,
                                               make_train_step)

log = get_logger()


def _dotted(tree) -> Dict[str, np.ndarray]:
    flat = {}

    def walk(prefix, t):
        if isinstance(t, dict):
            for k, v in t.items():
                walk(f"{prefix}.{k}" if prefix else k, v)
        else:
            flat[prefix] = np.asarray(t)
    walk("", tree)
    return flat


def _save_tree(d: str, tree) -> None:
    for name, arr in _dotted(tree).items():
        path = os.path.join(d, *name.split(".")) + ".npy"
        os.makedirs(os.path.dirname(path), exist_ok=True)
        np.save(path, arr)


def run_align_dump(out_dir: str, *,
                   trace_fn: Callable,
                   loss_fn: Callable,
                   trainable, frozen, batch: dict,
                   tc: TrainConfig, mask,
                   spec, family: str, model_dir: str,
                   steps: int = 5,
                   meta_extra: dict | None = None) -> dict:
    """Execute the align protocol and write the dump directory.

    trace_fn(trainable, frozen, batch) -> (logits, {"embed", "layers"})
    loss_fn: the trainer contract loss (sum_nll, weight).
    batch: ONE micro-batch (input_ids/attention_mask/labels).
    Returns the meta dict (also written to meta.json).
    """
    with jax.default_matmul_precision("highest"):
        return _run_align_dump(
            out_dir, trace_fn=trace_fn, loss_fn=loss_fn,
            trainable=trainable, frozen=frozen, batch=batch, tc=tc,
            mask=mask, spec=spec, family=family, model_dir=model_dir,
            steps=steps, meta_extra=meta_extra)


def _run_align_dump(out_dir, *, trace_fn, loss_fn, trainable, frozen,
                    batch, tc, mask, spec, family, model_dir, steps,
                    meta_extra):
    # Full-precision matmuls (caller's context manager): TPU's default
    # bf16-pass matmuls perturb near-zero gradients enough to flip signs,
    # and Adam's first step turns a sign flip on a zero-init B into a
    # +/-lr disagreement with the torch mirror.
    os.makedirs(out_dir, exist_ok=True)
    for k in ("input_ids", "attention_mask", "labels"):
        np.save(os.path.join(out_dir, f"batch_{k}.npy"),
                np.asarray(batch[k]))

    # ---- forward trace
    logits, acts = jax.jit(trace_fn)(trainable, frozen, batch)
    np.save(os.path.join(out_dir, "act_embed.npy"),
            np.asarray(acts["embed"], np.float32))
    layers = np.asarray(acts["layers"], np.float32)
    for i in range(layers.shape[0]):
        np.save(os.path.join(out_dir, f"act_layer_{i:02d}.npy"), layers[i])
    np.save(os.path.join(out_dir, "logits.npy"),
            np.asarray(logits, np.float32))

    # ---- loss + adapter grads (of the MEAN loss, matching HF reduction)
    def mean_loss(tr):
        s, w = loss_fn(tr, frozen, batch)
        return s / jnp.maximum(w, 1.0)

    loss0, grads = jax.jit(jax.value_and_grad(mean_loss))(trainable)
    np.save(os.path.join(out_dir, "loss.npy"),
            np.asarray(loss0, np.float32))
    _save_tree(os.path.join(out_dir, "grads"), grads)

    # ---- adapter pre + PEFT export for the mirror
    _save_tree(os.path.join(out_dir, "adapter_pre"),
               jax.device_get(trainable))
    peft_io.export_peft(os.path.join(out_dir, "peft"),
                        jax.device_get(trainable), spec, family,
                        base_model_name=model_dir)

    # ---- N steps on the SAME batch: post-step adapter + loss curve
    align_tc = dataclasses.replace(tc, schedule="constant",
                                   warmup_ratio=0.0, grad_accum_steps=1)
    step_fn = make_train_step(loss_fn, align_tc, mask=mask, donate=False)
    opt_state = init_optimizer(trainable, align_tc, mask)
    tr = trainable
    losses = []
    for s in range(max(steps, 1)):
        tr, opt_state, metrics = step_fn(tr, frozen, opt_state, batch,
                                         jnp.int32(s))
        losses.append(float(metrics["loss"]))
        if s == 0:
            _save_tree(os.path.join(out_dir, "adapter_post"),
                       jax.device_get(tr))
    np.save(os.path.join(out_dir, "losses.npy"),
            np.asarray(losses, np.float32))

    meta = {
        "family": family, "model_dir": os.path.abspath(model_dir),
        "lr": align_tc.lr, "weight_decay": align_tc.weight_decay,
        "clip_grad_norm": align_tc.clip_grad_norm,
        "coupled_weight_decay": align_tc.coupled_weight_decay,
        "steps": max(steps, 1), "rank": spec.rank, "alpha": spec.alpha,
        "targets": list(spec.targets or []),
        "n_layers": int(layers.shape[0]),
        "loss": float(loss0), "losses": [float(x) for x in losses],
    }
    meta.update(meta_extra or {})
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    log.info(f"align dump -> {out_dir} (loss={float(loss0):.6f}, "
             f"{steps} steps: {losses[0]:.6f} -> {losses[-1]:.6f})")
    return meta
